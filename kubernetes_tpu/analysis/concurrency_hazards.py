"""Pass 7 — concurrency-hazard & resource-lifecycle lint (CH7xx).

PRs 2, 7, and 12 established the runtime-robustness contracts that keep
the daemons alive at overload: classified exception handling instead of
silent swallows, ``_never_crash``-style observer isolation, bounded
queues with counted drops, resources closed in ``finally``, and no
blocking work under a lock that a wave or fan-out thread contends.
Until this pass they were enforced only by review convention.  Following
the PR 15 playbook (contracts become commit gates, not comments), this
pass turns each one into a rule over the race-lint scope plus the
ctypes shim and ``utils/`` (the telemetry/tracing daemon plumbing):

- **CH701** — a known-blocking call lexically under a held lock token,
  or in a method the caller-held-lock fixed point proves always runs
  with a lock held: socket/HTTP work (``urlopen``/``getresponse``/
  ``recv``/``accept``/``connect``/``sendall``), ``sleep``, thread
  ``join``, subprocess spawn/wait, ``fsync`` (the WAL durability
  point), event/future ``wait`` (a ``Condition`` in the class's lock
  tokens releases the lock — exempt), and device materialization per
  DC602's taint shapes (``.item()``/``.tolist()``/``device_get``/
  ``block_until_ready``).  Deliberate designs carry
  ``# blocking-ok — <reason>`` on the call's line or the line above;
  a reasonless annotation sanctions nothing.
- **CH702** — a swallowed exception: a bare ``except:`` /
  ``except Exception:`` / ``except BaseException:`` handler whose body
  neither re-raises, classifies, logs, nor counts — concretely, a body
  made ONLY of ``pass``/``continue``/``break``/valueless ``return``/
  constant expressions.  Any call (a logger, a counter ``.inc()``), any
  augmented assignment (``stats[...] += 1``), any state-recording
  assignment, or any ``raise`` is handling — over-approximate toward
  silence.  Handlers naming a narrower exception type are
  classification by construction and stay silent.
- **CH703** — resource lifecycle: a non-daemon ``Thread`` started with
  no reachable ``join`` (function-local threads join in the same
  function; ``self.<attr>`` threads join anywhere in the class),
  an ``open``/``urlopen``/``socket``/``create_connection`` result
  bound to a local that is never closed and never escapes (no
  ``with``, no ``.close()``, not returned/yielded/stored/passed on —
  any escape transfers ownership and silences), and a manually entered
  context manager (``x.__enter__()`` — the armed-``FaultPlan`` shape)
  with no matching ``.__exit__`` (function-wide for locals, class-wide
  for attributes).
- **CH704** — third-party callback invoked under a held lock: calling
  a handler/observer/callback-named loop variable or parameter (or one
  of its bound methods, including passing ``h.on_add`` into a
  dispatcher call) while a lock token is held.  Handler fan-out must
  follow the informer ``_deliver`` contract: snapshot the handler list
  under the lock, call outside it — foreign code under your lock can
  deadlock you or stall every peer.  Snapshotting itself
  (``list(self._handlers)``) and registration (``.append(handler)``)
  pass a container or a bare object, not a bound method, and stay
  silent.
- **CH705** — unbounded growth on daemon paths (classes with thread
  entries): a ``queue.Queue()`` constructed with no ``maxsize`` (or
  ``maxsize=0``) on an instance attribute, or a plain container
  attribute that worker-reachable code grows (``append``/``add``/
  ``setdefault``/variable-key subscript store/``heappush``) while NO
  method in the class ever shrinks or resets it.  Constant-string
  subscript stores (``stats["relists"] += 1``) are a fixed vocabulary,
  not growth.  Deliberate designs carry ``# bounded: <reason>`` on the
  construction or growth line (or the line above).

Deliberately NOT modeled, over-approximating toward silence: blocking
calls and callback invocations inside nested defs (they run at an
unknown time, possibly without the lock); threads stored in containers
(``self._threads.append(Thread(...))``); close-on-all-paths flow
analysis (CH703 is lexical: any ``.close()``/escape silences); growth
through aliases or collaborator objects; ``queue.get``/``put`` as
blocking shapes (indistinguishable from dict access by name).

The class machinery — MRO method tables, thread entries, attr-typed
collaborator lock tokens, and the caller-held-lock fixed point — is
the races pass's, imported rather than re-derived, so the two passes
can never disagree about what "under a lock" means.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .core import Finding, iter_py_files
from .races import (
    DEFAULT_PATHS as _RACES_PATHS,
    _ClassIndex,
    _callee_name,
    _container_attrs,
    _entry_held,
    _is_self_attr,
    _method_table,
    _reachable,
    _scan_methods,
    _self_attr_path,
    _thread_entries,
    _lock_tokens,
    _with_lock_token,
)

DEFAULT_PATHS = _RACES_PATHS + [
    # the ctypes shim: finalizer close paths (the first CH702 triage hit)
    "kubernetes_tpu/native.py",
    # telemetry/timeseries/tracing/health: the PR 12 daemon plumbing this
    # pass exists to keep honest (bounded queues, shipper threads)
    "kubernetes_tpu/utils",
]

_BLOCKING_OK_RE = re.compile(r"#\s*blocking-ok\s*(?:—|–|-{1,2})?\s*(.*)$")
_BOUNDED_RE = re.compile(r"#\s*bounded:\s*(.*)$")

#: bare-name calls that block (``from time import sleep``-style imports,
#: module-level helpers)
_BLOCKING_NAME_CALLS = {
    "sleep", "urlopen", "fsync", "check_output", "check_call", "Popen",
    "create_connection", "device_get",
}
#: attribute calls that block regardless of receiver (``time.sleep``,
#: ``self._sleep``, ``sock.recv`` …)
_BLOCKING_ATTR_CALLS = {
    "sleep", "urlopen", "getresponse", "fsync", "create_connection",
    "check_output", "check_call", "Popen", "communicate", "sendall",
    "recv", "accept", "connect", "device_get", "block_until_ready",
}
_CALLBACKISH = re.compile(
    r"(handler|observer|callback|listener|subscriber|hook)", re.I)
_OPEN_FACTORIES = {"open", "urlopen", "socket", "create_connection"}


def _annotated(ann: dict[int, Optional[str]], line: int) -> bool:
    """Sanctioned by a REASONED annotation on its own line or the line
    above (the ``# device: sync`` grammar, same placement rule)."""
    return bool(ann.get(line) or ann.get(line - 1))


def _scan_annotations(src: str) -> tuple[dict[int, Optional[str]], dict[int, Optional[str]]]:
    """(blocking-ok line -> reason-or-None, bounded line -> reason-or-None)."""
    blocking: dict[int, Optional[str]] = {}
    bounded: dict[int, Optional[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _BLOCKING_OK_RE.search(line)
        if m:
            blocking[i] = (m.group(1) or "").strip() or None
        m = _BOUNDED_RE.search(line)
        if m:
            bounded[i] = (m.group(1) or "").strip() or None
    return blocking, bounded


def _call_label(func: ast.expr) -> str:
    try:
        return ast.unparse(func)
    except Exception:  # pragma: no cover - unparse is total on parsed trees
        return _callee_name(func) or "<call>"


def _blocking_call(call: ast.Call, tokens: set[str]) -> Optional[str]:
    """A human label when ``call`` is a known-blocking shape, else None."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id in _BLOCKING_NAME_CALLS:
            return func.id
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr in _BLOCKING_ATTR_CALLS:
        return _call_label(func)
    if attr == "run" and isinstance(func.value, ast.Name) and func.value.id == "subprocess":
        return "subprocess.run"
    if attr == "join":
        # Thread.join() takes no args or a numeric timeout; str.join takes
        # exactly one iterable — an ambiguous single non-numeric arg stays
        # silent (over-approximate toward silence)
        if not call.args and not call.keywords:
            return _call_label(func)
        if any(kw.arg == "timeout" for kw in call.keywords):
            return _call_label(func)
        if (len(call.args) == 1 and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, (int, float))):
            return _call_label(func)
        return None
    if attr == "wait":
        # Condition.wait RELEASES the held lock — a receiver in the
        # class's lock tokens is the sanctioned sleep-under-lock shape.
        # An Event/Future/process wait on a self attribute does not.
        path = _self_attr_path(func.value)
        if path is not None and path not in tokens:
            return _call_label(func)
        return None
    if attr in ("item", "tolist") and not call.args and not call.keywords:
        # DC602's device-materialization shapes: a blocking device→host
        # round-trip is blocking work like any other
        return _call_label(func)
    return None


# -- CH701 / CH704: lock-context walk per method ----------------------------


class _LockSiteVisitor(ast.NodeVisitor):
    """Record blocking calls and callback invocations with the lock
    tokens lexically held at each site.  Nested defs are skipped — a
    closure runs at an unknown time, possibly without the lock."""

    def __init__(self, tokens: set[str], cb_aliases: dict[str, str],
                 cb_params: set[str]):
        self._tokens = tokens
        self._cb_aliases = cb_aliases  # local name -> callbackish attr
        self._cb_params = cb_params
        self._cb_loop_vars: dict[str, str] = {}  # loop var -> via-label
        self.held: list[str] = []
        self.blocking: list[tuple[str, int, frozenset]] = []
        self.callbacks: list[tuple[str, str, int, frozenset]] = []

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            tok = _with_lock_token(item.context_expr, self._tokens)
            if tok is not None:
                acquired.append(tok)
        self.held.extend(acquired)
        self.generic_visit(node)
        for _ in acquired:
            self.held.pop()

    def _callback_source(self, expr: ast.expr) -> Optional[str]:
        """The via-label when ``expr`` names a third-party callable: a
        loop var over a callbackish container, a callbackish local
        alias, or a callbackish parameter."""
        if not isinstance(expr, ast.Name):
            return None
        if expr.id in self._cb_loop_vars:
            return self._cb_loop_vars[expr.id]
        if expr.id in self._cb_params:
            return f"parameter `{expr.id}`"
        return None

    def visit_For(self, node: ast.For) -> None:
        bound = None
        it = node.iter
        # unwrap one snapshot wrapper: for h in list(self._handlers)
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id in ("list", "tuple", "sorted", "reversed")
                and it.args):
            it = it.args[0]
        attr = _is_self_attr(it)
        if attr is None and isinstance(it, ast.Name):
            attr = self._cb_aliases.get(it.id)
        if attr is not None and _CALLBACKISH.search(attr):
            if isinstance(node.target, ast.Name):
                bound = node.target.id
                self._cb_loop_vars[bound] = f"self.{attr}"
        self.generic_visit(node)
        if bound is not None:
            self._cb_loop_vars.pop(bound, None)

    def visit_Call(self, node: ast.Call) -> None:
        # record sites even when lexically bare: the caller-held fixed
        # point may prove this whole method runs under a lock (held0);
        # the reporter drops sites whose effective held set is empty
        label = _blocking_call(node, self._tokens)
        if label is not None:
            self.blocking.append((label, node.lineno, frozenset(self.held)))
        via = self._callback_source(node.func)
        if via is not None:
            self.callbacks.append(
                (_call_label(node.func), via, node.lineno,
                 frozenset(self.held)))
        elif isinstance(node.func, ast.Attribute):
            via = self._callback_source(node.func.value)
            if via is not None:
                self.callbacks.append(
                    (_call_label(node.func), via, node.lineno,
                     frozenset(self.held)))
        # passing a BOUND METHOD of a callback source into a call
        # hands foreign code to a dispatcher that will run it here,
        # under the lock (`self._deliver(handler.on_add, obj)`);
        # passing the bare object (registration) stays silent
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Attribute):
                via = self._callback_source(arg.value)
                if via is not None:
                    self.callbacks.append(
                        (_call_label(arg), via, node.lineno,
                         frozenset(self.held)))
        self.generic_visit(node)

    def visit_FunctionDef(self, node) -> None:
        return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _cb_local_aliases(fn: ast.FunctionDef) -> dict[str, str]:
    """Local names assigned (once is not required — any binding from a
    callbackish container makes later iteration suspect… but a REBOUND
    name is no longer provably the container, so require exactly one
    binding, mirroring the races alias rule)."""
    counts: dict[str, int] = {}
    cand: dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    counts[t.id] = counts.get(t.id, 0) + 1
                    value = node.value
                    if (isinstance(value, ast.Call)
                            and isinstance(value.func, ast.Name)
                            and value.func.id in ("list", "tuple", "sorted")
                            and value.args):
                        value = value.args[0]
                    attr = _is_self_attr(value)
                    if attr is not None and _CALLBACKISH.search(attr):
                        cand.setdefault(t.id, attr)
    return {n: a for n, a in cand.items() if counts.get(n) == 1}


# -- CH702: swallowed exceptions --------------------------------------------


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Attribute):
        names = [t.attr]
    elif isinstance(t, ast.Tuple):
        for el in t.elts:
            if isinstance(el, ast.Name):
                names.append(el.id)
            elif isinstance(el, ast.Attribute):
                names.append(el.attr)
    return any(n in ("Exception", "BaseException") for n in names)


def _swallows(handler: ast.ExceptHandler) -> bool:
    """True when the body does NOTHING with the exception: only pass/
    continue/break/valueless return/constant expressions.  Any call,
    raise, assignment, or control structure counts as handling
    (over-approximate toward silence)."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return):
            v = stmt.value
            if v is None or (isinstance(v, ast.Constant) and v.value is None):
                continue
            return False
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


# -- CH703: resource lifecycle ----------------------------------------------


def _thread_ctor(value: ast.expr) -> Optional[ast.Call]:
    if isinstance(value, ast.Call) and _callee_name(value.func) == "Thread":
        return value
    return None


def _is_daemon_ctor(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return bool(isinstance(kw.value, ast.Constant) and kw.value.value)
    return False


def _attr_calls_on(fn_or_fns, attr_name: str, path: bool = False):
    """All ``<target>.<attr_name>(...)`` calls where target is the given
    self-attr path (``path=True``) — yields (call, lineno)."""
    fns = fn_or_fns if isinstance(fn_or_fns, list) else [fn_or_fns]
    for fn in fns:
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                yield node, node.func


def _name_used_as(fn: ast.FunctionDef, name: str) -> dict[str, bool]:
    """How a local resource name is consumed in ``fn``: closed, entered
    as a with-context, or escaping (returned / yielded / stored onto an
    attribute or subscript / passed as a call argument)."""
    out = {"closed": False, "with": False, "escapes": False}
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Name) and ctx.id == name:
                    out["with"] = True
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "close"
                    and isinstance(f.value, ast.Name) and f.value.id == name):
                out["closed"] = True
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Starred):
                    arg = arg.value
                # a name inside a tuple/list argument still escapes —
                # `Thread(target=pump, args=(client, upstream))` hands the
                # socket to the pump threads, which own its close
                elts = (arg.elts if isinstance(arg, (ast.Tuple, ast.List))
                        else [arg])
                if any(isinstance(el, ast.Name) and el.id == name
                       for el in elts):
                    out["escapes"] = True
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = node.value
            if isinstance(v, ast.Name) and v.id == name:
                out["escapes"] = True
            elif isinstance(v, (ast.Tuple, ast.List)):
                if any(isinstance(el, ast.Name) and el.id == name
                       for el in v.elts):
                    out["escapes"] = True
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    v = node.value
                    if isinstance(v, ast.Name) and v.id == name:
                        out["escapes"] = True
    return out


class _FuncScope:
    __slots__ = ("node", "qualname")

    def __init__(self, node, qualname: str):
        self.node = node
        self.qualname = qualname


def _collect_funcs(tree: ast.Module) -> list[_FuncScope]:
    out: list[_FuncScope] = []

    def walk(node, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append(_FuncScope(child, q))
                walk(child, f"{q}.")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def _enclosing_qualname(tree: ast.Module, funcs: list[_FuncScope],
                        lineno: int) -> str:
    best = "<module>"
    best_span = None
    for f in funcs:
        end = getattr(f.node, "end_lineno", f.node.lineno)
        if f.node.lineno <= lineno <= end:
            span = end - f.node.lineno
            if best_span is None or span <= best_span:
                best, best_span = f.qualname, span
    return best


# -- the pass ---------------------------------------------------------------


def run(root: str, paths: Optional[list[str]] = None) -> list[Finding]:
    files = iter_py_files(root, paths or DEFAULT_PATHS)
    index = _ClassIndex(files)
    findings: list[Finding] = []
    reported: set[str] = set()

    def add(code: str, path: str, line: int, symbol: str, message: str) -> None:
        key = f"{code}:{path}:{symbol}"
        if key in reported:
            return
        reported.add(key)
        findings.append(Finding(code, path, line, symbol, message))

    for f in index.parse_errors:
        add("CH700", f.path, f.line, f.symbol, f.message)

    trees: dict[str, ast.Module] = {}
    blocking_ann: dict[str, dict[int, Optional[str]]] = {}
    bounded_ann: dict[str, dict[int, Optional[str]]] = {}
    for abs_path, rel in files:
        with open(abs_path, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            trees[rel] = ast.parse(src, filename=rel)
        except SyntaxError:
            continue  # already a CH700 via the index
        blocking_ann[rel], bounded_ann[rel] = _scan_annotations(src)

    # ---- per-file rules: CH702 swallows, CH703 local lifecycles ----------
    for rel in sorted(trees):
        tree = trees[rel]
        funcs = _collect_funcs(tree)
        swallow_ord: dict[str, int] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not (_is_broad_handler(node) and _swallows(node)):
                continue
            q = _enclosing_qualname(tree, funcs, node.lineno)
            n = swallow_ord[q] = swallow_ord.get(q, 0) + 1
            label = ("except:" if node.type is None
                     else f"except {_call_label(node.type)}:")
            add("CH702", rel, node.lineno, f"{q}.swallow{n}",
                f"`{label}` swallows the exception silently — the body "
                f"neither re-raises, classifies, logs, nor increments a "
                f"counter.  An invisible failure is unfixable in "
                f"production; at minimum count it (`….inc()` / "
                f"`stats[…] += 1`) and log at debug")
        for fs in funcs:
            _scan_function_lifecycle(fs, rel, add)

    # ---- per-class rules: CH701, CH703 attr-threads/CMs, CH704, CH705 ----
    class_infos = [
        info for key, info in sorted(index.classes.items()) if "::" in key
    ]
    for info in class_infos:
        table = _method_table(index, info)
        tokens = _lock_tokens(index, info)
        entries = _thread_entries(index, info)
        b_ann = blocking_ann.get(info.path, {})
        q_ann = bounded_ann.get(info.path, {})
        if tokens:
            _scan_lock_hazards(info, table, tokens, entries, b_ann, add)
        _scan_attr_lifecycle(info, table, add)
        if entries:
            _scan_unbounded(index, info, table, entries, q_ann, add)
    return findings


def _scan_lock_hazards(info, table, tokens, entries, b_ann, add) -> None:
    """CH701 + CH704 over every method of a lock-owning class.  'Under a
    lock' is lexical OR proven by the caller-held fixed point — roots
    (which hold nothing at entry) are the thread entries plus every
    public/dunder method; a private helper whose every caller holds the
    lock inherits the held set."""
    scans = _scan_methods(table, tokens)
    roots = sorted(set(entries)
                   | {m for m in table if not m.startswith("_")}
                   | {m for m in table
                      if m.startswith("__") and m.endswith("__")})
    at_entry = _entry_held(scans, roots, set(table))
    for meth in sorted(table):
        ci, fn = table[meth]
        cb_aliases = _cb_local_aliases(fn)
        cb_params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs
                                     + fn.args.posonlyargs)
                     if _CALLBACKISH.search(a.arg)}
        v = _LockSiteVisitor(tokens, cb_aliases, cb_params)
        held0 = at_entry.get(meth, frozenset())
        for stmt in fn.body:
            v.visit(stmt)
        for label, line, held in v.blocking:
            eff = held | held0
            if not eff:
                continue
            ann = b_ann if ci.path == info.path else {}
            if _annotated(ann, line):
                continue
            add("CH701", ci.path, line, f"{ci.name}.{meth}.{label}",
                f"blocking call `{label}(…)` under held lock "
                f"{'/'.join(sorted(eff))} — every thread contending this "
                f"lock stalls behind the I/O.  Move it outside the lock, "
                f"or annotate the line `# blocking-ok — <reason>` if the "
                f"blocking IS the contract (e.g. WAL fsync at the commit "
                f"point)")
        for label, via, line, held in v.callbacks:
            eff = held | held0
            if not eff:
                continue
            ann = b_ann if ci.path == info.path else {}
            if _annotated(ann, line):
                continue
            add("CH704", ci.path, line, f"{ci.name}.{meth}.{label}",
                f"third-party callback `{label}` (from {via}) invoked "
                f"under held lock {'/'.join(sorted(eff))} — foreign code "
                f"under your lock can deadlock you or stall every peer.  "
                f"Follow the informer `_deliver` contract: snapshot the "
                f"handler list under the lock, call outside it")
    # blocking/callback sites in methods the fixed point proves are
    # ALWAYS under a lock are reported above via held0; a lexically-bare
    # method reachable both ways stays silent (intersection semantics)


def _scan_function_lifecycle(fs: _FuncScope, rel: str, add) -> None:
    """CH703 over one function: local threads, local open-without-close,
    local manual ``__enter__``.  Nested defs have their own _FuncScope
    and report there."""
    fn = fs.node
    own: list[ast.stmt] = list(fn.body)

    def own_nodes():
        class V(ast.NodeVisitor):
            def __init__(self):
                self.nodes = []

            def generic_visit(self, node):
                self.nodes.append(node)
                super().generic_visit(node)

            def visit_FunctionDef(self, node):
                return

            visit_AsyncFunctionDef = visit_FunctionDef
            visit_Lambda = visit_FunctionDef

        v = V()
        for stmt in own:
            v.visit(stmt)
        return v.nodes

    nodes = own_nodes()
    # local threads: t = Thread(...); t.start() with no t.join()
    threads: dict[str, tuple[ast.Call, int]] = {}
    daemonized: set[str] = set()
    started: set[str] = set()
    joined: set[str] = set()
    entered: dict[str, int] = {}
    exited: set[str] = set()
    for node in nodes:
        if isinstance(node, ast.Assign):
            ctor = _thread_ctor(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if ctor is not None:
                        threads[t.id] = (ctor, node.lineno)
                        if _is_daemon_ctor(ctor):
                            daemonized.add(t.id)
                elif (isinstance(t, ast.Attribute) and t.attr == "daemon"
                        and isinstance(t.value, ast.Name)):
                    if isinstance(node.value, ast.Constant) and node.value.value:
                        daemonized.add(t.value.id)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if isinstance(recv, ast.Name):
                if node.func.attr == "start":
                    started.add(recv.id)
                elif node.func.attr == "join":
                    joined.add(recv.id)
                elif node.func.attr == "__enter__":
                    entered.setdefault(recv.id, node.lineno)
                elif node.func.attr == "__exit__":
                    exited.add(recv.id)
            # Thread(...).start() — fire-and-forget, never joinable
            elif (node.func.attr == "start"
                    and isinstance(recv, ast.Call)
                    and _callee_name(recv.func) == "Thread"
                    and not _is_daemon_ctor(recv)):
                add("CH703", rel, node.lineno,
                    f"{fs.qualname}.thread.anonymous",
                    "non-daemon Thread started fire-and-forget — it can "
                    "never be joined, so process shutdown blocks on it "
                    "forever if its loop doesn't exit.  Keep a handle and "
                    "join it, or pass daemon=True")
    for name, (ctor, line) in threads.items():
        if name in started and name not in daemonized and name not in joined:
            add("CH703", rel, line, f"{fs.qualname}.thread.{name}",
                f"non-daemon Thread `{name}` started with no reachable "
                f"join in this function — a crashed owner leaks the "
                f"thread past shutdown.  join it (a `finally` is the "
                f"honest place) or pass daemon=True")
    for name, line in entered.items():
        if name not in exited:
            add("CH703", rel, line, f"{fs.qualname}.enter.{name}",
                f"`{name}.__enter__()` with no matching `{name}."
                f"__exit__` in this function — a manually entered "
                f"context manager (an armed FaultPlan, a held lock) "
                f"must be released in a `finally`, or the failure path "
                f"leaves it armed forever")
    # local open-without-close
    for node in nodes:
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        cname = _callee_name(node.value.func)
        if cname not in _OPEN_FACTORIES:
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            use = _name_used_as(fn, t.id)
            if use["closed"] or use["with"] or use["escapes"]:
                continue
            add("CH703", rel, node.lineno, f"{fs.qualname}.open.{t.id}",
                f"`{t.id} = {cname}(…)` is never closed and never "
                f"escapes this function — the handle leaks on every "
                f"call.  Use `with`, close it in a `finally`, or hand "
                f"it to an owner that closes it")


def _scan_attr_lifecycle(info, table, add) -> None:
    """CH703 for ``self.<attr>`` threads and manually entered CMs: the
    join / ``__exit__`` may live in any method of the class."""
    attr_threads: dict[str, tuple[int, str, str]] = {}  # attr -> (line, path, meth)
    attr_daemon: set[str] = set()
    attr_started: set[str] = set()
    attr_joined: set[str] = set()
    attr_entered: dict[str, tuple[int, str, str]] = {}
    attr_exited: set[str] = set()
    for meth in sorted(table):
        ci, fn = table[meth]
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                ctor = _thread_ctor(node.value)
                for t in node.targets:
                    attr = _is_self_attr(t)
                    if attr is not None and ctor is not None:
                        attr_threads.setdefault(
                            attr, (node.lineno, ci.path, f"{ci.name}.{meth}"))
                        if _is_daemon_ctor(ctor):
                            attr_daemon.add(attr)
                    elif (isinstance(t, ast.Attribute) and t.attr == "daemon"):
                        base = _is_self_attr(t.value)
                        if (base is not None
                                and isinstance(node.value, ast.Constant)
                                and node.value.value):
                            attr_daemon.add(base)
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                path = _self_attr_path(node.func.value)
                if path is None:
                    continue
                if node.func.attr == "start":
                    attr_started.add(path)
                elif node.func.attr == "join":
                    attr_joined.add(path)
                elif node.func.attr == "__enter__":
                    attr_entered.setdefault(
                        path, (node.lineno, ci.path, f"{ci.name}.{meth}"))
                elif node.func.attr == "__exit__":
                    attr_exited.add(path)
    for attr, (line, path, where) in sorted(attr_threads.items()):
        if (attr in attr_started and attr not in attr_daemon
                and attr not in attr_joined):
            add("CH703", path, line, f"{where}.thread.{attr}",
                f"non-daemon Thread `self.{attr}` started with no "
                f"`self.{attr}.join(…)` anywhere in the class — shutdown "
                f"can never reclaim it.  join it in stop()/close(), or "
                f"pass daemon=True")
    for attr, (line, path, where) in sorted(attr_entered.items()):
        if attr not in attr_exited:
            add("CH703", path, line, f"{where}.enter.{attr}",
                f"`self.{attr}.__enter__()` with no matching "
                f"`self.{attr}.__exit__` anywhere in the class — the "
                f"armed state leaks if no method ever releases it")


_GROW_MUTATORS = {"append", "appendleft", "add", "setdefault", "insert",
                  "extend"}
_SHRINK_MUTATORS = {"pop", "popleft", "popitem", "remove", "discard",
                    "clear"}
_QUEUE_FACTORIES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}


def _scan_unbounded(index, info, table, entries, q_ann, add) -> None:
    """CH705 over a thread-entry class: unbounded stdlib queues on
    attributes, and plain containers that worker-reachable code grows
    while nothing in the class ever shrinks or resets them."""
    containers = _container_attrs(index, info)
    reachable = _reachable(table, entries)

    def _assign_targets(node):
        if isinstance(node, ast.Assign) and node.value is not None:
            return node.targets, node.value
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            return [node.target], node.value
        return [], None

    # attrs constructed as bounded deques (`deque(maxlen=N)` / second
    # positional arg): maxlen evicts on append — growth there is bounded
    # by construction and must stay silent
    bounded_attrs: set[str] = set()
    for meth in sorted(table):
        _, fn = table[meth]
        for node in ast.walk(fn):
            targets, value = _assign_targets(node)
            if not isinstance(value, ast.Call):
                continue
            if _callee_name(value.func) != "deque":
                continue
            has_bound = len(value.args) >= 2 or any(
                kw.arg == "maxlen" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value in (None, 0))
                for kw in value.keywords)
            if not has_bound:
                continue
            for t in targets:
                attr = _is_self_attr(t)
                if attr is not None:
                    bounded_attrs.add(attr)
    containers = {a for a in containers if a not in bounded_attrs}

    # queue constructions
    for meth in sorted(table):
        ci, fn = table[meth]
        for node in ast.walk(fn):
            targets, value = _assign_targets(node)
            if not isinstance(value, ast.Call):
                continue
            cname = _callee_name(value.func)
            if cname not in _QUEUE_FACTORIES:
                continue
            call = value
            bounded = bool(call.args) or any(
                kw.arg == "maxsize" and not (
                    isinstance(kw.value, ast.Constant) and kw.value.value == 0)
                for kw in call.keywords)
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and call.args[0].value == 0:
                bounded = False
            if cname == "SimpleQueue":
                bounded = False  # SimpleQueue has no bound at all
            if bounded:
                continue
            for t in targets:
                attr = _is_self_attr(t)
                if attr is None:
                    continue
                ann = q_ann if ci.path == info.path else {}
                if _annotated(ann, node.lineno):
                    continue
                add("CH705", ci.path, node.lineno,
                    f"{ci.name}.{meth}.{attr}",
                    f"`self.{attr} = {cname}()` with no maxsize on a "
                    f"daemon path (thread entries: {'/'.join(entries)}) — "
                    f"a stalled consumer grows it without limit.  Bound "
                    f"it and count drops, or annotate "
                    f"`# bounded: <reason>` naming the real backpressure")
    # grow-without-shrink containers
    grows: dict[str, tuple[int, str, str, str]] = {}
    shrinks: set[str] = set()
    for meth in sorted(table):
        ci, fn = table[meth]
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                attr = _is_self_attr(node.func.value)
                if attr in containers:
                    if node.func.attr in _SHRINK_MUTATORS:
                        shrinks.add(attr)
                    elif (node.func.attr in _GROW_MUTATORS
                            and meth in reachable and meth != "__init__"):
                        grows.setdefault(attr, (
                            node.lineno, ci.path, f"{ci.name}.{meth}",
                            f".{node.func.attr}()"))
                name = _callee_name(node.func)
                if name in ("heappush", "heappop") and node.args:
                    attr = _is_self_attr(node.args[0])
                    if attr in containers:
                        if name == "heappop":
                            shrinks.add(attr)
                        elif meth in reachable and meth != "__init__":
                            grows.setdefault(attr, (
                                node.lineno, ci.path,
                                f"{ci.name}.{meth}", "heappush()"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        base = t.value
                        attr = _is_self_attr(base)
                        if attr in containers:
                            # constant-string keys are a fixed vocabulary
                            # (stats counters), not unbounded growth
                            if (isinstance(t.slice, ast.Constant)
                                    and isinstance(t.slice.value, str)):
                                continue
                            if isinstance(node, ast.Assign) and \
                                    meth in reachable and meth != "__init__":
                                grows.setdefault(attr, (
                                    node.lineno, ci.path,
                                    f"{ci.name}.{meth}", "subscript store"))
                    else:
                        attr = _is_self_attr(t)
                        if (attr in containers and meth != "__init__"
                                and isinstance(node, ast.Assign)):
                            shrinks.add(attr)  # wholesale rebind = reset
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _is_self_attr(t.value)
                        if attr in containers:
                            shrinks.add(attr)
    for attr, (line, path, where, what) in sorted(grows.items()):
        if attr in shrinks:
            continue
        ann = q_ann if path == info.path else {}
        if _annotated(ann, line):
            continue
        add("CH705", path, line, f"{where}.{attr}",
            f"container `self.{attr}` grows ({what}) on a worker-"
            f"reachable path (thread entries: {'/'.join(entries)}) and "
            f"NO method of {info.name} ever shrinks or resets it — "
            f"unbounded growth on a daemon path.  Evict somewhere, or "
            f"annotate the growth line `# bounded: <reason>`")
