"""Pass 3 — controller/kubelet race lint (RL3xx).

The controller runtime's production shape is threaded (``run_workers``,
``controllers/base.py:110``): informer handlers enqueue on the event
thread, N workers pop keys and run ``sync``.  The reference survives this
because every mutable map it touches is lock-guarded; a Python port loses
that discipline one convenience attribute at a time.  This pass walks the
``threading.Thread`` target call graph and reports:

- RL301: an instance attribute *assigned* (``self.x = …`` / ``self.x += …``)
  inside a worker-thread-reachable method without holding one of the
  object's own locks.  Lock attributes are those assigned
  ``threading.Lock()/RLock()/Condition()`` anywhere in the class (MRO
  included); a write is "held" when lexically inside ``with self.<lock>:``.
- RL302: a lock-acquisition-order cycle — method A acquires lock1 then
  (directly or via one self-call) lock2, while method B acquires them in
  the opposite order.
- RL303: a *plain-container* instance attribute (one assigned a
  dict/list/set/deque literal or constructor in this class) mutated from
  a worker-reachable method without a lock — subscript writes/deletes,
  mutator method calls (``.append``/``.pop``/``.update``/…), and
  ``heapq.heappush/heappop`` on the attribute.  Restricting to
  known-plain containers is what keeps internally-locked objects
  (``WorkQueue``, informer stores) from false-positiving.

Resolution is name-based MRO over the scanned packages: thread entry
points found in a base class (``Controller._worker_loop``) make the
*subclass* ``sync`` overrides worker-reachable, which is exactly where
convenience writes accumulate.  Informer-handler callbacks
(``Handler(on_add=self.m)``, ``watch(kind, key_fn=self.m)``) count as
thread entries too — they fire on the informer's ``_run_loop`` thread in
the production shape; lambdas in those slots are unwrapped
(``on_update=lambda old, new: self._move(old, new)`` marks ``_move``).
HTTP handler ``do_*`` methods are deliberately NOT entry points — there
is no special-case code, they simply match none of the entry heuristics
— because the stdlib server builds a NEW handler instance per
connection, so ``self`` is thread-confined and per-request attribute
writes are not races.  (A handler class that ALSO spawns a thread over
shared state is analyzed through that thread entry like any other
class.)  Lock-order cycles are checked for every class that defines
locks, entries or not.

Aliased mutations (``p = self._pending; p[k] = v``) ARE tracked for the
single-assignment case (ISSUE 5, first slice of the points-to-lite
item): a local name assigned exactly ONCE in the method, from a plain
``self.<container>`` read, is treated as that container — subscript
writes/deletes, mutator calls, and heap functions on it report RL301/
RL303 exactly as the direct form would.  Chains of such names
(``q = p; q[k] = v`` — the ISSUE 6 slice) resolve by fixed point, so a
two-hop (or k-hop) alias reports identically; a name reassigned
anywhere in the method (including loop/with targets) or shadowing a
parameter breaks the chain at that link and everything downstream is
dropped: flow-insensitive alias tracking must over-approximate toward
SILENCE, never invent findings on a rebound local.

Known blind spots (documented, deliberate): aliases captured by nested
defs, aliases flowing through calls/containers (``q = f(p)``,
``pair = (p,); pair[0][k] = v``), and locks held by callers across
method boundaries are not tracked (a method that writes under "caller
holds the lock" convention baselines with that as its justification).
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Finding, iter_py_files

DEFAULT_PATHS = [
    "kubernetes_tpu/controllers",
    "kubernetes_tpu/kubelet",
    "kubernetes_tpu/client",
    "kubernetes_tpu/scheduler",
    "kubernetes_tpu/apiserver",
    "kubernetes_tpu/auth",
    "kubernetes_tpu/dns",
    "kubernetes_tpu/proxy",
    "kubernetes_tpu/store",
    # ISSUE 2 scope extension (ROADMAP open item): the federation/cloud/
    # admission layers, the CLI, and the daemon supervisor run informer
    # callbacks and timer loops too — triaged clean on extension (these
    # trees are almost thread-free; daemon.py's single Thread only
    # supervises subprocesses it owns)
    "kubernetes_tpu/federation",
    "kubernetes_tpu/cloud",
    "kubernetes_tpu/admission",
    "kubernetes_tpu/cli",
    "kubernetes_tpu/daemon.py",
    # the fault framework itself: armed/disarmed from test threads while
    # hit() runs on any thread — keep it under the race lint
    "kubernetes_tpu/faults",
]

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
CONTAINER_FACTORIES = {
    "dict",
    "list",
    "set",
    "deque",
    "defaultdict",
    "OrderedDict",
    "Counter",
}
MUTATOR_METHODS = {
    "append",
    "appendleft",
    "extend",
    "add",
    "remove",
    "discard",
    "pop",
    "popleft",
    "popitem",
    "update",
    "setdefault",
    "clear",
    "insert",
}
HEAP_FUNCS = {"heappush", "heappop", "heappushpop", "heapreplace", "heapify"}


class ClassInfo:
    def __init__(self, name: str, node: ast.ClassDef, path: str):
        self.name = name
        self.node = node
        self.path = path
        self.bases = [_base_name(b) for b in node.bases]
        self.methods: dict[str, ast.FunctionDef] = {
            item.name: item
            for item in node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }


def _base_name(expr: ast.expr) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


def _is_self_attr(expr: ast.expr) -> Optional[str]:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


class _ClassIndex:
    def __init__(self, files: list[tuple[str, str]]):
        self.classes: dict[str, ClassInfo] = {}
        self.parse_errors: list[Finding] = []
        for abs_path, rel in files:
            with open(abs_path, "r", encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError as e:
                self.parse_errors.append(
                    Finding("RL300", rel, e.lineno or 1, "syntax", f"unparseable file: {e.msg}")
                )
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    # same-named classes across modules: last wins is wrong;
                    # key by (module, name) and by bare name for base lookup
                    info = ClassInfo(node.name, node, rel)
                    self.classes.setdefault(node.name, info)
                    self.classes[f"{rel}::{node.name}"] = info

    def mro(self, info: ClassInfo) -> list[ClassInfo]:
        """Name-based linearization (left-to-right DFS, dedup)."""
        out: list[ClassInfo] = []
        seen: set[int] = set()

        def visit(ci: ClassInfo) -> None:
            if id(ci) in seen:
                return
            seen.add(id(ci))
            out.append(ci)
            for b in ci.bases:
                base = self.classes.get(b)
                if base is not None:
                    visit(base)

        visit(info)
        return out


def _method_table(index: _ClassIndex, info: ClassInfo) -> dict[str, tuple[ClassInfo, ast.FunctionDef]]:
    table: dict[str, tuple[ClassInfo, ast.FunctionDef]] = {}
    for ci in reversed(index.mro(info)):
        for name, fn in ci.methods.items():
            table[name] = (ci, fn)
    return table


def _lock_attrs(index: _ClassIndex, info: ClassInfo) -> set[str]:
    locks: set[str] = set()
    for ci in index.mro(info):
        for fn in ci.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    callee = node.value.func
                    factory = (
                        callee.attr if isinstance(callee, ast.Attribute)
                        else callee.id if isinstance(callee, ast.Name) else ""
                    )
                    if factory in LOCK_FACTORIES:
                        for t in node.targets:
                            attr = _is_self_attr(t)
                            if attr:
                                locks.add(attr)
    return locks


def _container_attrs(index: _ClassIndex, info: ClassInfo) -> set[str]:
    """Attributes assigned a plain dict/list/set/deque (literal or
    constructor) anywhere in the class — the objects with no interior
    locking of their own."""
    out: set[str] = set()
    for ci in index.mro(info):
        for fn in ci.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if value is None:
                    continue
                is_container = isinstance(
                    value, (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
                )
                if not is_container and isinstance(value, ast.Call):
                    callee = value.func
                    name = (
                        callee.attr if isinstance(callee, ast.Attribute)
                        else callee.id if isinstance(callee, ast.Name) else ""
                    )
                    is_container = name in CONTAINER_FACTORIES
                if not is_container:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    attr = _is_self_attr(t)
                    if attr:
                        out.add(attr)
    return out


def _thread_entries(index: _ClassIndex, info: ClassInfo) -> list[str]:
    """Method names of ``info`` (via its table) that run on worker threads
    against a SHARED instance (HTTP handler ``do_*`` methods are excluded:
    one instance per connection means no cross-thread instance state)."""
    entries: set[str] = set()
    table = _method_table(index, info)
    for _ci, fn in table.values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            cname = (
                callee.attr if isinstance(callee, ast.Attribute)
                else callee.id if isinstance(callee, ast.Name) else ""
            )
            if cname not in ("Thread", "Timer"):
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = _is_self_attr(kw.value)
                    if attr and attr in table:
                        entries.add(attr)
            # Timer(interval, self.m)
            if cname == "Timer" and len(node.args) >= 2:
                attr = _is_self_attr(node.args[1])
                if attr and attr in table:
                    entries.add(attr)
    # informer-handler convention: callbacks registered via
    # Handler(on_add=self.m, …) or watch(kind, key_fn=self.m) run on the
    # informer's _run_loop thread in the production shape
    for _ci, fn in table.values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg in ("on_add", "on_update", "on_delete", "key_fn"):
                    attr = _is_self_attr(kw.value)
                    if attr and attr in table:
                        entries.add(attr)
                    elif isinstance(kw.value, ast.Lambda):
                        # on_update=lambda old, new: self._move(old, new)
                        for n in ast.walk(kw.value.body):
                            attr = _is_self_attr(n) if isinstance(n, ast.Attribute) else None
                            if attr and attr in table:
                                entries.add(attr)
    return sorted(entries)


def _reachable(table: dict, entries: list[str]) -> set[str]:
    seen: set[str] = set()
    stack = list(entries)
    while stack:
        m = stack.pop()
        if m in seen or m not in table:
            continue
        seen.add(m)
        _ci, fn = table[m]
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                attr = _is_self_attr(node.func)
                if attr and attr in table and attr not in seen:
                    stack.append(attr)
    return seen


def _subscript_self_attr(target: ast.expr) -> Optional[str]:
    """`self.x[k]` (possibly nested subscripts) -> "x"."""
    while isinstance(target, ast.Subscript):
        target = target.value
    return _is_self_attr(target)


def _subscript_name(target: ast.expr) -> Optional[str]:
    """`p[k]` (possibly nested subscripts) -> "p"."""
    while isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Name):
        return target.id
    return None


def _local_aliases(fn: ast.FunctionDef, containers: set[str]) -> dict[str, str]:
    """Local name -> container attribute, for names assigned exactly once
    in ``fn`` (nested defs excluded, mirroring _WriteVisitor's scope) and
    whose one assignment is a plain ``self.<container>`` read — or, the
    ISSUE 6 points-to slice, a chain of such names (``p = self._pending;
    q = p; q[k] = v``): name→name links between single-assignment locals
    resolve to the container by fixed point, so a two-hop (or k-hop)
    alias reports exactly as the direct form would.  Any other binding of
    ANY name in the chain — a second assignment, a for/with target, a
    parameter — breaks the chain at that link and every name past it is
    dropped (flow-insensitive tracking must never flag a rebound local)."""
    counts: dict[str, int] = {}
    cand: dict[str, str] = {}
    links: dict[str, str] = {}  # q -> p for single-candidate `q = p`
    params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs
                              + fn.args.posonlyargs)}
    if fn.args.vararg is not None:
        params.add(fn.args.vararg.arg)
    if fn.args.kwarg is not None:
        params.add(fn.args.kwarg.arg)

    def bind(name: str) -> None:
        counts[name] = counts.get(name, 0) + 1

    def bind_target(t: ast.expr) -> None:
        # only NAME bindings count: a subscript/attribute store
        # (``p[k] = v``) mutates the referent, it does not rebind ``p``
        if isinstance(t, ast.Name):
            bind(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                bind_target(el)
        elif isinstance(t, ast.Starred):
            bind_target(t.value)

    class V(ast.NodeVisitor):
        def visit_Assign(self, node: ast.Assign) -> None:
            for t in node.targets:
                bind_target(t)
                if isinstance(t, ast.Name):
                    attr = _is_self_attr(node.value)
                    if attr is not None and attr in containers:
                        cand[t.id] = attr
                    elif isinstance(node.value, ast.Name):
                        # `q = p`: a name-to-name link — resolved to a
                        # container only if the whole chain survives the
                        # single-assignment filter below
                        links[t.id] = node.value.id
            self.generic_visit(node)

        def visit_AugAssign(self, node: ast.AugAssign) -> None:
            bind_target(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
            if node.value is not None:
                bind_target(node.target)
            self.generic_visit(node)

        def visit_NamedExpr(self, node) -> None:
            bind_target(node.target)
            self.generic_visit(node)

        def visit_For(self, node: ast.For) -> None:
            bind_target(node.target)
            self.generic_visit(node)

        def visit_With(self, node: ast.With) -> None:
            for item in node.items:
                if item.optional_vars is not None:
                    bind_target(item.optional_vars)
            self.generic_visit(node)

        def visit_FunctionDef(self, node) -> None:
            return  # nested defs execute elsewhere (same as _WriteVisitor)

        visit_AsyncFunctionDef = visit_FunctionDef

    v = V()
    for stmt in fn.body:
        v.visit(stmt)

    def valid(name: str) -> bool:
        return counts.get(name) == 1 and name not in params

    resolved = {name: attr for name, attr in cand.items() if valid(name)}
    # fixed point over the name→name links: `q = p` resolves to p's
    # container only when BOTH names are single-assignment non-params —
    # a rebound or shadowed link anywhere in the chain drops everything
    # downstream of it (over-approximate toward silence)
    chain_links = {q: p for q, p in links.items()
                   if valid(q) and q not in resolved}
    changed = True
    while changed:
        changed = False
        for q, p in chain_links.items():
            if q not in resolved and p in resolved:
                resolved[q] = resolved[p]
                changed = True
    return resolved


class _WriteVisitor(ast.NodeVisitor):
    """Find self-attribute writes/mutations and the lock context they run
    under.  ``writes`` are rebinding assignments (RL301); ``mutations``
    are container-interior writes (RL303)."""

    def __init__(self, locks: set[str], containers: set[str],
                 aliases: Optional[dict[str, str]] = None):
        self.locks = locks
        self.containers = containers
        # single-assignment local aliases of container attributes
        # (``p = self._pending``): mutations through them count against
        # the aliased attribute (see _local_aliases)
        self.aliases = aliases or {}
        self.held: list[str] = []
        self.writes: list[tuple[str, int, frozenset]] = []  # (attr, line, held)
        self.mutations: list[tuple[str, int, frozenset, str]] = []  # +what

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            ctx = item.context_expr
            attr = _is_self_attr(ctx)
            if attr is None and isinstance(ctx, ast.Call):
                attr = _is_self_attr(ctx.func)  # with self._mu: vs self._cond:
            if attr in self.locks:
                acquired.append(attr)
        self.held.extend(acquired)
        self.generic_visit(node)
        for _ in acquired:
            self.held.pop()

    def _record(self, target: ast.expr, line: int) -> None:
        attr = _is_self_attr(target)
        if attr is not None:
            self.writes.append((attr, line, frozenset(self.held)))
            return
        attr = _subscript_self_attr(target)
        if attr is not None and attr in self.containers:
            self.mutations.append((attr, line, frozenset(self.held), "subscript write"))
            return
        if isinstance(target, ast.Subscript):
            name = _subscript_name(target)
            if name is not None and name in self.aliases:
                self.mutations.append((
                    self.aliases[name], line, frozenset(self.held),
                    f"subscript write via alias `{name}`"))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            attr = _subscript_self_attr(t)
            if attr is not None and attr in self.containers:
                self.mutations.append((attr, node.lineno, frozenset(self.held), "del"))
                continue
            if isinstance(t, ast.Subscript):
                name = _subscript_name(t)
                if name is not None and name in self.aliases:
                    self.mutations.append((
                        self.aliases[name], node.lineno, frozenset(self.held),
                        f"del via alias `{name}`"))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATOR_METHODS:
            attr = _is_self_attr(fn.value)
            if attr is not None and attr in self.containers:
                self.mutations.append(
                    (attr, node.lineno, frozenset(self.held), f".{fn.attr}()")
                )
            elif (isinstance(fn.value, ast.Name)
                    and fn.value.id in self.aliases):
                self.mutations.append((
                    self.aliases[fn.value.id], node.lineno,
                    frozenset(self.held),
                    f".{fn.attr}() via alias `{fn.value.id}`"))
        else:
            hname = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else ""
            )
            if hname in HEAP_FUNCS and node.args:
                attr = _is_self_attr(node.args[0])
                if attr is not None and attr in self.containers:
                    self.mutations.append(
                        (attr, node.lineno, frozenset(self.held), f"{hname}()")
                    )
                elif (isinstance(node.args[0], ast.Name)
                        and node.args[0].id in self.aliases):
                    self.mutations.append((
                        self.aliases[node.args[0].id], node.lineno,
                        frozenset(self.held),
                        f"{hname}() via alias `{node.args[0].id}`"))
        self.generic_visit(node)

    # nested defs (callbacks) execute elsewhere; analyzed separately
    def visit_FunctionDef(self, node) -> None:
        return

    visit_AsyncFunctionDef = visit_FunctionDef


def _lock_order_edges(
    table: dict, locks: set[str]
) -> dict[tuple[str, str], tuple[str, str, int]]:
    """(lockA, lockB) -> (class, method, line) where A is held when B is
    acquired, expanding one level of self-calls."""
    # first: per-method, top-level acquisitions + (held -> acquired) pairs
    method_acquires: dict[str, list[str]] = {}
    edges: dict[tuple[str, str], tuple[str, str, int]] = {}

    class V(ast.NodeVisitor):
        def __init__(self, cls_name: str, meth: str):
            self.cls = cls_name
            self.meth = meth
            self.held: list[str] = []
            self.calls_under: list[tuple[str, frozenset, int]] = []

        def visit_With(self, node: ast.With) -> None:
            acquired = []
            for item in node.items:
                ctx = item.context_expr
                attr = _is_self_attr(ctx)
                if attr is None and isinstance(ctx, ast.Call):
                    attr = _is_self_attr(ctx.func)
                if attr in locks:
                    acquired.append(attr)
                    if not self.held:
                        method_acquires.setdefault(self.meth, []).append(attr)
                    for h in self.held:
                        if h != attr:
                            edges.setdefault((h, attr), (self.cls, self.meth, node.lineno))
            self.held.extend(acquired)
            self.generic_visit(node)
            for _ in acquired:
                self.held.pop()

        def visit_Call(self, node: ast.Call) -> None:
            attr = _is_self_attr(node.func)
            if attr and self.held:
                self.calls_under.append((attr, frozenset(self.held), node.lineno))
            self.generic_visit(node)

        def visit_FunctionDef(self, node) -> None:
            return

        visit_AsyncFunctionDef = visit_FunctionDef

    visitors: list[V] = []
    for meth, (ci, fn) in table.items():
        v = V(ci.name, meth)
        for stmt in fn.body:
            v.visit(stmt)
        visitors.append(v)
    # one level of call expansion: caller holds H, callee acquires A at top
    for v in visitors:
        for callee, held, line in v.calls_under:
            for a in method_acquires.get(callee, ()):
                for h in held:
                    if h != a:
                        edges.setdefault((h, a), (v.cls, f"{v.meth}->{callee}", line))
    return edges


def _find_cycles(edges: dict) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: list[list[str]] = []
    seen_cycles: set[frozenset] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(path + [start])
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return cycles


def run(root: str, paths: Optional[list[str]] = None) -> list[Finding]:
    files = iter_py_files(root, paths or DEFAULT_PATHS)
    index = _ClassIndex(files)
    findings: list[Finding] = list(index.parse_errors)
    reported: set[str] = set()

    class_infos = [
        info for key, info in sorted(index.classes.items()) if "::" in key
    ]
    for info in class_infos:
        table = _method_table(index, info)
        entries = _thread_entries(index, info)
        locks = _lock_attrs(index, info)
        if not entries:
            if locks:
                _report_lock_cycles(info, table, locks, findings, reported)
            continue
        containers = _container_attrs(index, info)
        reachable = _reachable(table, entries)
        for meth in sorted(reachable):
            ci, fn = table[meth]
            if meth == "__init__":
                continue  # runs on the constructing (main) thread
            visitor = _WriteVisitor(locks, containers,
                                    aliases=_local_aliases(fn, containers))
            for stmt in fn.body:
                visitor.visit(stmt)
            for attr, line, held in visitor.writes:
                if attr in locks or held:
                    continue
                # report at the DEFINING class so subclasses don't duplicate
                symbol = f"{ci.name}.{meth}.{attr}"
                key = f"RL301:{ci.path}:{symbol}"
                if key in reported:
                    continue
                reported.add(key)
                findings.append(
                    Finding(
                        code="RL301",
                        path=ci.path,
                        line=line,
                        symbol=symbol,
                        message=(
                            f"`self.{attr}` assigned in worker-thread-reachable "
                            f"method `{meth}` (entry: {'/'.join(entries)}) without "
                            f"holding any of the object's locks "
                            f"({', '.join(sorted(locks)) or 'none defined'})"
                        ),
                    )
                )
            for attr, line, held, what in visitor.mutations:
                if held:
                    continue
                symbol = f"{ci.name}.{meth}.{attr}"
                key = f"RL303:{ci.path}:{symbol}"
                if key in reported:
                    continue
                reported.add(key)
                findings.append(
                    Finding(
                        code="RL303",
                        path=ci.path,
                        line=line,
                        symbol=symbol,
                        message=(
                            f"container `self.{attr}` mutated ({what}) in "
                            f"worker-thread-reachable method `{meth}` (entry: "
                            f"{'/'.join(entries)}) without holding any of the "
                            f"object's locks "
                            f"({', '.join(sorted(locks)) or 'none defined'})"
                        ),
                    )
                )
        # lock-order cycles (per concrete class; report at defining site)
        _report_lock_cycles(info, table, locks, findings, reported)
    return findings


def _report_lock_cycles(
    info: ClassInfo,
    table: dict,
    locks: set[str],
    findings: list[Finding],
    reported: set[str],
) -> None:
    edges = _lock_order_edges(table, locks)
    for cycle in _find_cycles(edges):
        a, b = cycle[0], cycle[1]
        cls, meth, line = edges[(a, b)]
        symbol = f"{cls}.lockcycle.{'-'.join(cycle[:-1])}"
        key = f"RL302:{info.path}:{symbol}"
        if key in reported:
            continue
        reported.add(key)
        findings.append(
            Finding(
                code="RL302",
                path=info.path,
                line=line,
                symbol=symbol,
                message=(
                    f"lock-acquisition-order cycle {' -> '.join(cycle)} "
                    f"(first edge in {cls}.{meth}): two threads taking these "
                    f"locks in opposite orders deadlock"
                ),
            )
        )
