"""Pass 3 — controller/kubelet race lint (RL3xx).

The controller runtime's production shape is threaded (``run_workers``,
``controllers/base.py:110``): informer handlers enqueue on the event
thread, N workers pop keys and run ``sync``.  The reference survives this
because every mutable map it touches is lock-guarded; a Python port loses
that discipline one convenience attribute at a time.  This pass walks the
``threading.Thread`` target call graph and reports:

- RL301: an instance attribute *assigned* (``self.x = …`` / ``self.x += …``)
  inside a worker-thread-reachable method without holding one of the
  object's own locks.  Lock attributes are those assigned
  ``threading.Lock()/RLock()/Condition()`` anywhere in the class (MRO
  included); a write is "held" when lexically inside ``with self.<lock>:``.
- RL302: a lock-acquisition-order cycle — method A acquires lock1 then
  (directly or via one self-call) lock2, while method B acquires them in
  the opposite order.
- RL303: a *plain-container* instance attribute (one assigned a
  dict/list/set/deque literal or constructor in this class) mutated from
  a worker-reachable method without a lock — subscript writes/deletes,
  mutator method calls (``.append``/``.pop``/``.update``/…), and
  ``heapq.heappush/heappop`` on the attribute.  Restricting to
  known-plain containers is what keeps internally-locked objects
  (``WorkQueue``, informer stores) from false-positiving.

Resolution is name-based MRO over the scanned packages: thread entry
points found in a base class (``Controller._worker_loop``) make the
*subclass* ``sync`` overrides worker-reachable, which is exactly where
convenience writes accumulate.  Informer-handler callbacks
(``Handler(on_add=self.m)``, ``watch(kind, key_fn=self.m)``) count as
thread entries too — they fire on the informer's ``_run_loop`` thread in
the production shape; lambdas in those slots are unwrapped
(``on_update=lambda old, new: self._move(old, new)`` marks ``_move``).
HTTP handler ``do_*`` methods are deliberately NOT entry points — there
is no special-case code, they simply match none of the entry heuristics
— because the stdlib server builds a NEW handler instance per
connection, so ``self`` is thread-confined and per-request attribute
writes are not races.  (A handler class that ALSO spawns a thread over
shared state is analyzed through that thread entry like any other
class.)  Lock-order cycles are checked for every class that defines
locks, entries or not.

The points-to-lite layer (grown across ISSUEs 5/6/10) tracks how shared
containers travel before they are mutated:

- **local aliases** (ISSUE 5/6): a local name assigned exactly ONCE in
  the method, from a plain ``self.<container>`` read, is treated as that
  container; chains (``q = p; q[k] = v``) resolve by fixed point.  A
  name reassigned anywhere in the method or shadowing a parameter breaks
  the chain at that link and everything downstream is dropped:
  flow-insensitive alias tracking must over-approximate toward SILENCE,
  never invent findings on a rebound local.
- **aliases through calls and returns** (ISSUE 10): per-function return
  summaries — "returns ``self.<attr>``" / "returns argument ``p``" /
  "returns ``self``" — are computed for every method in the class table
  and every module-level function in the class's file, iterated to fixed
  point through the call graph, so ``q = self._get_pending()`` and
  ``q = self._identity(p)`` (and chains of such calls) resolve to the
  container.  A function whose return statements disagree, or return
  anything else (a copy, a literal), has no summary and its callers stay
  silent.
- **cross-object lock identity** (ISSUE 10): lock names are attribute
  *paths*.  ``self.queue = WorkQueue()`` plus ``WorkQueue.__init__``
  assigning ``self._cond = Condition()`` makes ``queue._cond`` a lock
  token of this class, so ``with self.queue._cond:`` guards writes
  exactly like an own lock, and RL302 cycles are tracked across the two
  objects' locks.  Attribute types resolve only through direct
  constructor calls (``self.x = ClassName(...)``) — a lock path on an
  attribute of unknown type is NOT a guard (status quo), and cannot
  silence anything it could not already.
- **caller-held locks** (ISSUE 10): a helper method reachable ONLY
  through call sites that hold a lock (``def _slot(self): …`` called
  from three ``with self._mu:`` blocks — the PodOwnerIndex shape) is
  analyzed with that lock held at entry.  The held-at-entry set is the
  INTERSECTION over every worker-reachable call edge, iterated to fixed
  point, so one unlocked call site strips the guarantee.
- **nested-def captures** (ISSUE 10): closures and lambdas no longer
  terminate the walk — a nested def that mutates ``self.<container>`` or
  a captured alias reports at the enclosing worker-reachable method
  (where the thread entry is), tagged with the closure's name.  Locks
  held at the def site count as held (the closure may run later without
  them, but flagging would invent findings on every callback built under
  a lock — over-approximate toward silence); names the closure rebinds
  or takes as parameters shadow the enclosing aliases.
- **one-hop container extraction** (ISSUE 10): ``x = self._items[k]``
  (or ``x = p[k]`` through a container alias) makes ``x`` an *element*
  alias — mutator calls, subscript writes/deletes, and heap functions on
  it report RL303 against the container attribute.  One hop only:
  ``x = self._items[k][j]`` and aliases flowing through tuples/lists
  (``pair = (p,); pair[0][k] = v``) remain out of scope (documented in
  ROADMAP).
- **cross-object reachability** (ISSUE 10): a worker-reachable method
  calling ``self.<attr>.<m>(...)`` — or a *bound-method alias*
  (``self.metrics = self.metrics_client.utilization`` then
  ``self.metrics(p)``) — on an attribute typed by a constructor call
  makes ``<m>`` an external thread entry of the collaborator class: its
  unguarded writes are analyzed exactly as if it spawned the thread
  itself.  This is the dual of cross-object lock identity, and the shape
  that found the MetricsClient race (no threads of its own; every mutation
  reached from HPA controller workers).  One hop only: externally-entered
  classes do not propagate entries onward to THEIR collaborators
  (documented in ROADMAP).
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Finding, iter_py_files

DEFAULT_PATHS = [
    "kubernetes_tpu/controllers",
    "kubernetes_tpu/kubelet",
    "kubernetes_tpu/client",
    "kubernetes_tpu/scheduler",
    "kubernetes_tpu/apiserver",
    "kubernetes_tpu/auth",
    "kubernetes_tpu/dns",
    "kubernetes_tpu/proxy",
    "kubernetes_tpu/store",
    # ISSUE 2 scope extension (ROADMAP open item): the federation/cloud/
    # admission layers, the CLI, and the daemon supervisor run informer
    # callbacks and timer loops too — triaged clean on extension (these
    # trees are almost thread-free; daemon.py's single Thread only
    # supervises subprocesses it owns)
    "kubernetes_tpu/federation",
    "kubernetes_tpu/cloud",
    "kubernetes_tpu/admission",
    "kubernetes_tpu/cli",
    "kubernetes_tpu/daemon.py",
    # the fault framework itself: armed/disarmed from test threads while
    # hit() runs on any thread — keep it under the race lint
    "kubernetes_tpu/faults",
]

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
CONTAINER_FACTORIES = {
    "dict",
    "list",
    "set",
    "deque",
    "defaultdict",
    "OrderedDict",
    "Counter",
}
MUTATOR_METHODS = {
    "append",
    "appendleft",
    "extend",
    "add",
    "remove",
    "discard",
    "pop",
    "popleft",
    "popitem",
    "update",
    "setdefault",
    "clear",
    "insert",
}
HEAP_FUNCS = {"heappush", "heappop", "heappushpop", "heapreplace", "heapify"}


class ClassInfo:
    def __init__(self, name: str, node: ast.ClassDef, path: str):
        self.name = name
        self.node = node
        self.path = path
        self.bases = [_base_name(b) for b in node.bases]
        self.methods: dict[str, ast.FunctionDef] = {
            item.name: item
            for item in node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }


def _base_name(expr: ast.expr) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


def _is_self_attr(expr: ast.expr) -> Optional[str]:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def _self_attr_path(expr: ast.expr) -> Optional[str]:
    """``self.a`` -> "a"; ``self.a._cond`` -> "a._cond" (any depth)."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if parts and isinstance(expr, ast.Name) and expr.id == "self":
        return ".".join(reversed(parts))
    return None


def _callee_name(expr: ast.expr) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


class _ClassIndex:
    def __init__(self, files: list[tuple[str, str]]):
        self.classes: dict[str, ClassInfo] = {}
        self.module_funcs: dict[str, dict[str, ast.FunctionDef]] = {}
        self.parse_errors: list[Finding] = []
        for abs_path, rel in files:
            with open(abs_path, "r", encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError as e:
                self.parse_errors.append(
                    Finding("RL300", rel, e.lineno or 1, "syntax", f"unparseable file: {e.msg}")
                )
                continue
            # top-level functions, for return-summary resolution of
            # `q = f(p)` calls (aliases through module-level helpers)
            self.module_funcs[rel] = {
                node.name: node
                for node in tree.body
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    # same-named classes across modules: last wins is wrong;
                    # key by (module, name) and by bare name for base lookup
                    info = ClassInfo(node.name, node, rel)
                    self.classes.setdefault(node.name, info)
                    self.classes[f"{rel}::{node.name}"] = info

    def mro(self, info: ClassInfo) -> list[ClassInfo]:
        """Name-based linearization (left-to-right DFS, dedup)."""
        out: list[ClassInfo] = []
        seen: set[int] = set()

        def visit(ci: ClassInfo) -> None:
            if id(ci) in seen:
                return
            seen.add(id(ci))
            out.append(ci)
            for b in ci.bases:
                base = self.classes.get(b)
                if base is not None:
                    visit(base)

        visit(info)
        return out


def _method_table(index: _ClassIndex, info: ClassInfo) -> dict[str, tuple[ClassInfo, ast.FunctionDef]]:
    table: dict[str, tuple[ClassInfo, ast.FunctionDef]] = {}
    for ci in reversed(index.mro(info)):
        for name, fn in ci.methods.items():
            table[name] = (ci, fn)
    return table


def _lock_attrs(index: _ClassIndex, info: ClassInfo) -> set[str]:
    locks: set[str] = set()
    for ci in index.mro(info):
        for fn in ci.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    factory = _callee_name(node.value.func)
                    if factory in LOCK_FACTORIES:
                        for t in node.targets:
                            attr = _is_self_attr(t)
                            if attr:
                                locks.add(attr)
    return locks


def _attr_types(index: _ClassIndex, info: ClassInfo) -> dict[str, ClassInfo]:
    """``self.x = ClassName(...)`` anywhere in the class (MRO) resolves
    the attribute's type when ``ClassName`` is a scanned class — the
    cross-object half of lock-path identity.  The dependency-injection
    default ``self.x = injected or ClassName(...)`` types from the
    constructor operand (the production shape; an injected substitute is
    a test concern).  Attributes assigned from parameters or other call
    results stay untyped (no guess, no silence)."""
    out: dict[str, ClassInfo] = {}
    for ci in index.mro(info):
        for fn in ci.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                values = [node.value]
                if isinstance(node.value, ast.BoolOp) and isinstance(
                        node.value.op, ast.Or):
                    values = list(node.value.values)
                target_cls = None
                for value in values:
                    if isinstance(value, ast.Call):
                        target_cls = index.classes.get(
                            _callee_name(value.func))
                        if target_cls is not None:
                            break
                if target_cls is None:
                    continue
                for t in node.targets:
                    attr = _is_self_attr(t)
                    if attr:
                        out.setdefault(attr, target_cls)
    return out


def _lock_tokens(index: _ClassIndex, info: ClassInfo) -> set[str]:
    """Every lock identity this class can hold via ``with self.<path>:`` —
    its own lock attributes plus one-hop cross-object paths
    (``queue._cond`` when ``self.queue`` resolves to a class whose
    ``_cond`` is a lock)."""
    tokens = set(_lock_attrs(index, info))
    for attr, cls in _attr_types(index, info).items():
        for lock in _lock_attrs(index, cls):
            tokens.add(f"{attr}.{lock}")
    return tokens


def _with_lock_token(item_ctx: ast.expr, tokens: set[str]) -> Optional[str]:
    """The lock token a ``with`` item acquires, or None."""
    path = _self_attr_path(item_ctx)
    if path is None and isinstance(item_ctx, ast.Call):
        path = _self_attr_path(item_ctx.func)  # with self._mu: vs self._cond:
    if path is not None and path in tokens:
        return path
    return None


def _container_attrs(index: _ClassIndex, info: ClassInfo) -> set[str]:
    """Attributes assigned a plain dict/list/set/deque (literal or
    constructor) anywhere in the class — the objects with no interior
    locking of their own."""
    out: set[str] = set()
    for ci in index.mro(info):
        for fn in ci.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if value is None:
                    continue
                is_container = isinstance(
                    value, (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
                )
                if not is_container and isinstance(value, ast.Call):
                    is_container = _callee_name(value.func) in CONTAINER_FACTORIES
                if not is_container:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    attr = _is_self_attr(t)
                    if attr:
                        out.add(attr)
    return out


def _thread_entries(index: _ClassIndex, info: ClassInfo) -> list[str]:
    """Method names of ``info`` (via its table) that run on worker threads
    against a SHARED instance (HTTP handler ``do_*`` methods are excluded:
    one instance per connection means no cross-thread instance state)."""
    entries: set[str] = set()
    table = _method_table(index, info)
    for _ci, fn in table.values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            cname = _callee_name(node.func)
            if cname not in ("Thread", "Timer"):
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = _is_self_attr(kw.value)
                    if attr and attr in table:
                        entries.add(attr)
            # Timer(interval, self.m)
            if cname == "Timer" and len(node.args) >= 2:
                attr = _is_self_attr(node.args[1])
                if attr and attr in table:
                    entries.add(attr)
    # informer-handler convention: callbacks registered via
    # Handler(on_add=self.m, …) or watch(kind, key_fn=self.m) run on the
    # informer's _run_loop thread in the production shape
    for _ci, fn in table.values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg in ("on_add", "on_update", "on_delete", "key_fn"):
                    attr = _is_self_attr(kw.value)
                    if attr and attr in table:
                        entries.add(attr)
                    elif isinstance(kw.value, ast.Lambda):
                        # on_update=lambda old, new: self._move(old, new)
                        for n in ast.walk(kw.value.body):
                            attr = _is_self_attr(n) if isinstance(n, ast.Attribute) else None
                            if attr and attr in table:
                                entries.add(attr)
    return sorted(entries)


def _reachable(table: dict, entries: list[str]) -> set[str]:
    seen: set[str] = set()
    stack = list(entries)
    while stack:
        m = stack.pop()
        if m in seen or m not in table:
            continue
        seen.add(m)
        _ci, fn = table[m]
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                attr = _is_self_attr(node.func)
                if attr and attr in table and attr not in seen:
                    stack.append(attr)
    return seen


class _HeldCallScanner(ast.NodeVisitor):
    """Per-method scan shared by the lock-order pass and caller-held-lock
    propagation: records top-level lock acquisitions, (held → acquired)
    edges, and every self-call with the lock set lexically held at the
    call site.  Nested defs are skipped here — a closure's calls run at
    an unknown time, so they can neither prove a caller-held lock nor
    order an acquisition."""

    def __init__(self, tokens: set[str]):
        self._tokens = tokens
        self.held: list[str] = []
        self.top_acquires: list[tuple[str, int]] = []
        self.edges: list[tuple[str, str, int]] = []  # (held, acquired, line)
        self.calls: list[tuple[str, frozenset, int]] = []  # (callee, held, line)

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            tok = _with_lock_token(item.context_expr, self._tokens)
            if tok is not None:
                acquired.append(tok)
                if not self.held:
                    self.top_acquires.append((tok, node.lineno))
                for h in self.held:
                    if h != tok:
                        self.edges.append((h, tok, node.lineno))
        self.held.extend(acquired)
        self.generic_visit(node)
        for _ in acquired:
            self.held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        attr = _is_self_attr(node.func)
        if attr:
            self.calls.append((attr, frozenset(self.held), node.lineno))
        self.generic_visit(node)

    def visit_FunctionDef(self, node) -> None:
        return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _scan_methods(table: dict, tokens: set[str]) -> dict[str, _HeldCallScanner]:
    scans: dict[str, _HeldCallScanner] = {}
    for meth, (_ci, fn) in table.items():
        sc = _HeldCallScanner(tokens)
        for stmt in fn.body:
            sc.visit(stmt)
        scans[meth] = sc
    return scans


def _entry_held(
    scans: dict[str, _HeldCallScanner],
    entries: list[str],
    reachable: set[str],
) -> dict[str, frozenset]:
    """Locks provably held at ENTRY of each worker-reachable method: the
    intersection over every worker-reachable call edge, to fixed point
    (the PodOwnerIndex shape — a private helper whose every caller is
    inside ``with self._mu:``).  Thread entries run bare by definition;
    a method reachable through even one unlocked edge loses the guard."""
    UNKNOWN = None  # lattice top: no edge seen yet
    state: dict[str, Optional[frozenset]] = {m: UNKNOWN for m in reachable}
    for e in entries:
        state[e] = frozenset()
    changed = True
    while changed:
        changed = False
        for m in sorted(reachable):
            held_in = state.get(m)
            if held_in is None or m not in scans:
                continue
            for callee, held, _line in scans[m].calls:
                if callee not in reachable or callee in entries:
                    continue
                eff = held_in | held
                cur = state.get(callee)
                new = eff if cur is None else frozenset(cur & eff)
                if new != cur:
                    state[callee] = new
                    changed = True
    return {m: (s if s is not None else frozenset()) for m, s in state.items()}


# -- return summaries (aliases through calls/returns) -----------------------


def _own_returns(fn: ast.FunctionDef) -> list[ast.Return]:
    """Return statements of ``fn`` itself (nested defs excluded — their
    returns are not this function's)."""
    out: list[ast.Return] = []

    class V(ast.NodeVisitor):
        def visit_Return(self, node: ast.Return) -> None:
            out.append(node)

        def visit_FunctionDef(self, node) -> None:
            return

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    for stmt in fn.body:
        V().visit(stmt)
    return out


def _param_names(fn: ast.FunctionDef) -> list[str]:
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


def _call_arg_for_param(call: ast.Call, fn: ast.FunctionDef,
                        pname: str, *, is_method: bool) -> Optional[ast.expr]:
    """The argument expression a call binds to parameter ``pname`` of
    ``fn`` (positional or keyword; starred/ambiguous forms resolve to
    None — silence)."""
    if any(isinstance(a, ast.Starred) for a in call.args):
        return None
    params = _param_names(fn)
    if is_method and params and params[0] == "self":
        params = params[1:]
    for kw in call.keywords:
        if kw.arg == pname:
            return kw.value
    try:
        i = params.index(pname)
    except ValueError:
        return None
    if i < len(call.args):
        return call.args[i]
    return None


def _return_summaries(
    table: dict, module_funcs: dict[str, ast.FunctionDef]
) -> dict[tuple, tuple]:
    """Fixed-point per-function return summaries over the class's method
    table plus its module's top-level functions.  Values:
    ``("attr", name)`` — every return is ``self.<name>`` (possibly
    through further summarized calls); ``("arg", pname)`` — every return
    is the same parameter; ``("self",)`` — returns self;
    ``("tuple", (elem, ...))`` — every return is a same-arity tuple
    LITERAL, each element summarized positionally (an element whose
    returns disagree or resolve to nothing is ``None`` — that position
    simply aliases nothing).  A function whose returns disagree or
    return anything else has no summary."""
    fns: dict[tuple, tuple[ast.FunctionDef, bool]] = {}
    for meth, (_ci, fn) in table.items():
        fns[("m", meth)] = (fn, True)
    for name, fn in module_funcs.items():
        fns[("f", name)] = (fn, False)
    summaries: dict[tuple, Optional[tuple]] = {k: None for k in fns}

    def resolve(expr: ast.expr, params: set[str], depth: int) -> Optional[tuple]:
        if depth > 8:
            return None
        attr = _is_self_attr(expr)
        if attr is not None:
            return ("attr", attr)
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return ("self",)
            if expr.id in params:
                return ("arg", expr.id)
            return None
        if isinstance(expr, ast.Call):
            callee_key = None
            meth = _is_self_attr(expr.func)
            if meth is not None and ("m", meth) in fns:
                callee_key = ("m", meth)
            elif (isinstance(expr.func, ast.Name)
                    and ("f", expr.func.id) in fns):
                callee_key = ("f", expr.func.id)
            if callee_key is None:
                return None
            summary = summaries[callee_key]
            if summary is None:
                return None
            if summary[0] in ("attr", "self"):
                return summary
            if summary[0] == "arg":
                callee_fn, is_method = fns[callee_key]
                arg = _call_arg_for_param(expr, callee_fn, summary[1],
                                          is_method=is_method)
                if arg is None:
                    return None
                return resolve(arg, params, depth + 1)
        return None

    # summaries only move bottom→value as callee summaries fill in, so
    # recomputation is monotone and terminates
    changed = True
    while changed:
        changed = False
        for key, (fn, is_method) in fns.items():
            if summaries[key] is not None:
                continue
            returns = _own_returns(fn)
            if not returns or any(r.value is None for r in returns):
                continue
            params = set(_param_names(fn))
            if is_method:
                params.discard("self")
            # tuple-literal returns summarize positionally (ISSUE 16):
            # `return self._q, self._mu` feeds `a, b = self._pair()`
            if all(isinstance(r.value, ast.Tuple) for r in returns):
                arities = {len(r.value.elts) for r in returns}
                has_star = any(isinstance(el, ast.Starred)
                               for r in returns for el in r.value.elts)
                if len(arities) == 1 and not has_star:
                    elems = []
                    for i in range(arities.pop()):
                        vals = {resolve(r.value.elts[i], params, 0)
                                for r in returns}
                        elems.append(vals.pop() if len(vals) == 1 else None)
                    if any(e is not None for e in elems):
                        summaries[key] = ("tuple", tuple(elems))
                        changed = True
                continue
            resolved = {resolve(r.value, params, 0) for r in returns}
            if len(resolved) == 1:
                val = resolved.pop()
                if val is not None:
                    summaries[key] = val
                    changed = True
    return {k: v for k, v in summaries.items() if v is not None}


def _subscript_self_attr(target: ast.expr) -> Optional[str]:
    """`self.x[k]` (possibly nested subscripts) -> "x"."""
    while isinstance(target, ast.Subscript):
        target = target.value
    return _is_self_attr(target)


def _subscript_name(target: ast.expr) -> Optional[str]:
    """`p[k]` (possibly nested subscripts) -> "p"."""
    while isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Name):
        return target.id
    return None


def _local_aliases(
    fn: ast.FunctionDef,
    containers: set[str],
    summaries: Optional[dict[tuple, tuple]] = None,
    fns: Optional[dict] = None,
) -> tuple[dict[str, str], dict[str, str]]:
    """(container aliases, element aliases): local name -> container
    attribute, for names assigned exactly once in ``fn`` (nested defs
    excluded, mirroring _WriteVisitor's scope).  A container alias's one
    assignment is a plain ``self.<container>`` read, a chain of such
    names (``p = self._pending; q = p``) resolved by fixed point, or —
    the ISSUE 10 slice — a call whose return summary resolves to the
    container (``q = self._get_pending()``, ``q = self._identity(p)``,
    ``q = ident(p)`` for a module-level helper).  Tuple unpacking with
    matching arity and no starred target (``a, b = self._x, self._y``)
    aliases pairwise — each (target, value) pair is handled exactly as
    its standalone assignment would be.  An element alias is a
    ONE-HOP extraction ``x = self._items[k]`` (directly or through a
    container alias).  Any other binding of ANY name in a chain — a
    second assignment, a for/with target, a parameter — breaks the chain
    at that link and every name past it is dropped (flow-insensitive
    tracking must never flag a rebound local)."""
    summaries = summaries or {}
    fns = fns or {}
    counts: dict[str, int] = {}
    cand: dict[str, str] = {}
    links: dict[str, str] = {}  # q -> p for single-candidate `q = p`
    # q -> (container-name-or-attr, via) for one-hop subscript reads;
    # resolved after the container aliases are known
    elem_reads: dict[str, ast.expr] = {}
    params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs
                              + fn.args.posonlyargs)}
    if fn.args.vararg is not None:
        params.add(fn.args.vararg.arg)
    if fn.args.kwarg is not None:
        params.add(fn.args.kwarg.arg)

    def bind(name: str) -> None:
        counts[name] = counts.get(name, 0) + 1

    def bind_target(t: ast.expr) -> None:
        # only NAME bindings count: a subscript/attribute store
        # (``p[k] = v``) mutates the referent, it does not rebind ``p``
        if isinstance(t, ast.Name):
            bind(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                bind_target(el)
        elif isinstance(t, ast.Starred):
            bind_target(t.value)

    def resolve_call(value: ast.Call, depth: int = 0) -> Optional[tuple]:
        """What a call returns, through the summaries: ("attr", a) or
        ("name", local) — the latter feeds the chain links."""
        if depth > 8:
            return None
        callee_key = None
        meth = _is_self_attr(value.func)
        if meth is not None and ("m", meth) in fns:
            callee_key = ("m", meth)
        elif isinstance(value.func, ast.Name) and ("f", value.func.id) in fns:
            callee_key = ("f", value.func.id)
        if callee_key is None:
            return None
        summary = summaries.get(callee_key)
        if summary is None or summary[0] == "self":
            return None
        if summary[0] == "attr":
            return summary
        # ("arg", pname): the alias IS whatever was passed
        callee_fn, is_method = fns[callee_key]
        arg = _call_arg_for_param(value, callee_fn, summary[1],
                                  is_method=is_method)
        if arg is None:
            return None
        attr = _is_self_attr(arg)
        if attr is not None:
            return ("attr", attr)
        if isinstance(arg, ast.Name):
            return ("name", arg.id)
        if isinstance(arg, ast.Call):
            return resolve_call(arg, depth + 1)
        return None

    def resolve_call_tuple(value: ast.Call) -> Optional[list]:
        """Per-position aliases for a call with a ``("tuple", ...)``
        summary: each element becomes ("attr", a) / ("name", local) /
        None (that position aliases nothing)."""
        callee_key = None
        meth = _is_self_attr(value.func)
        if meth is not None and ("m", meth) in fns:
            callee_key = ("m", meth)
        elif isinstance(value.func, ast.Name) and ("f", value.func.id) in fns:
            callee_key = ("f", value.func.id)
        if callee_key is None:
            return None
        summary = summaries.get(callee_key)
        if summary is None or summary[0] != "tuple":
            return None
        callee_fn, is_method = fns[callee_key]
        out: list = []
        for elem in summary[1]:
            if elem is None or elem[0] == "self":
                out.append(None)
            elif elem[0] == "attr":
                out.append(elem)
            else:  # ("arg", pname): the alias IS whatever was passed
                arg = _call_arg_for_param(value, callee_fn, elem[1],
                                          is_method=is_method)
                attr = _is_self_attr(arg) if arg is not None else None
                if attr is not None:
                    out.append(("attr", attr))
                elif isinstance(arg, ast.Name):
                    out.append(("name", arg.id))
                else:
                    out.append(None)
        return out

    def handle_pair(t: ast.expr, value: ast.expr) -> None:
        if isinstance(t, ast.Name):
            attr = _is_self_attr(value)
            if attr is not None and attr in containers:
                cand[t.id] = attr
            elif isinstance(value, ast.Name):
                # `q = p`: a name-to-name link — resolved to a
                # container only if the whole chain survives the
                # single-assignment filter below
                links[t.id] = value.id
            elif isinstance(value, ast.Call):
                got = resolve_call(value)
                if got is not None:
                    if got[0] == "attr" and got[1] in containers:
                        cand[t.id] = got[1]
                    elif got[0] == "name":
                        links[t.id] = got[1]
            elif (isinstance(value, ast.Subscript)
                    and not isinstance(value.value, ast.Subscript)):
                # one-hop element extraction: x = self._items[k]
                # or x = p[k]; resolved below once container
                # aliases are known
                elem_reads[t.id] = value.value
        elif isinstance(t, (ast.Tuple, ast.List)):
            starred = [i for i, el in enumerate(t.elts)
                       if isinstance(el, ast.Starred)]
            if (isinstance(value, (ast.Tuple, ast.List))
                    and not any(isinstance(el, ast.Starred)
                                for el in value.elts)):
                if not starred and len(value.elts) == len(t.elts):
                    # matching arity, no stars: each (target, value) pair
                    # aliases exactly as the standalone assignment would
                    for sub_t, sub_v in zip(t.elts, value.elts):
                        handle_pair(sub_t, sub_v)
                elif len(starred) == 1 and len(value.elts) >= len(t.elts) - 1:
                    # one starred TARGET against a literal value (ISSUE
                    # 16): positions before the star align with the value
                    # prefix, positions after with the value suffix; the
                    # starred name binds a FRESH list and aliases nothing
                    s = starred[0]
                    n_suffix = len(t.elts) - s - 1
                    for sub_t, sub_v in zip(t.elts[:s], value.elts[:s]):
                        handle_pair(sub_t, sub_v)
                    if n_suffix:
                        for sub_t, sub_v in zip(
                                t.elts[s + 1:],
                                value.elts[len(value.elts) - n_suffix:]):
                            handle_pair(sub_t, sub_v)
            elif isinstance(value, ast.Call) and not starred:
                # call-returned tuple unpacking (ISSUE 16): a callee
                # whose every return is a same-arity tuple literal
                # aliases positionally; arity mismatch, starred targets,
                # or an unsummarized callee stay unmodeled (silence)
                got = resolve_call_tuple(value)
                if got is not None and len(got) == len(t.elts):
                    for sub_t, elem in zip(t.elts, got):
                        if elem is None or not isinstance(sub_t, ast.Name):
                            continue
                        if elem[0] == "attr" and elem[1] in containers:
                            cand[sub_t.id] = elem[1]
                        elif elem[0] == "name":
                            links[sub_t.id] = elem[1]
            # any other unpacking shape stays unmodeled (silence)

    class V(ast.NodeVisitor):
        def visit_Assign(self, node: ast.Assign) -> None:
            for t in node.targets:
                bind_target(t)
                handle_pair(t, node.value)
            self.generic_visit(node)

        def visit_AugAssign(self, node: ast.AugAssign) -> None:
            bind_target(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
            if node.value is not None:
                bind_target(node.target)
            self.generic_visit(node)

        def visit_NamedExpr(self, node) -> None:
            bind_target(node.target)
            self.generic_visit(node)

        def visit_For(self, node: ast.For) -> None:
            bind_target(node.target)
            self.generic_visit(node)

        def visit_With(self, node: ast.With) -> None:
            for item in node.items:
                if item.optional_vars is not None:
                    bind_target(item.optional_vars)
            self.generic_visit(node)

        def visit_FunctionDef(self, node) -> None:
            return  # nested defs execute elsewhere (same as _WriteVisitor)

        visit_AsyncFunctionDef = visit_FunctionDef

    v = V()
    for stmt in fn.body:
        v.visit(stmt)

    def valid(name: str) -> bool:
        return counts.get(name) == 1 and name not in params

    resolved = {name: attr for name, attr in cand.items() if valid(name)}
    # fixed point over the name→name links: `q = p` resolves to p's
    # container only when BOTH names are single-assignment non-params —
    # a rebound or shadowed link anywhere in the chain drops everything
    # downstream of it (over-approximate toward silence)
    chain_links = {q: p for q, p in links.items()
                   if valid(q) and q not in resolved}
    changed = True
    while changed:
        changed = False
        for q, p in chain_links.items():
            if q not in resolved and p in resolved:
                resolved[q] = resolved[p]
                changed = True
    elems: dict[str, str] = {}
    for name, base in elem_reads.items():
        if not valid(name) or name in resolved:
            continue
        attr = _is_self_attr(base)
        if attr is None and isinstance(base, ast.Name):
            attr = resolved.get(base.id)
        if attr is not None and attr in containers:
            elems[name] = attr
    return resolved, elems


class _WriteVisitor(ast.NodeVisitor):
    """Find self-attribute writes/mutations and the lock context they run
    under.  ``writes`` are rebinding assignments (RL301); ``mutations``
    are container-interior writes (RL303).  Nested defs/lambdas are
    walked too (ISSUE 10): their writes report at the enclosing method
    (tagged with the closure name), def-site locks count as held, and
    names they rebind or take as parameters shadow the enclosing
    aliases."""

    def __init__(self, locks: set[str], containers: set[str],
                 aliases: Optional[dict[str, str]] = None,
                 elem_aliases: Optional[dict[str, str]] = None):
        self.locks = locks
        self.containers = containers
        # single-assignment local aliases of container attributes
        # (``p = self._pending``): mutations through them count against
        # the aliased attribute (see _local_aliases)
        self.aliases = aliases or {}
        # one-hop element extractions (``x = self._items[k]``)
        self.elem_aliases = elem_aliases or {}
        self.held: list[str] = []
        self.nested: list[str] = []  # enclosing closure names, if any
        # (attr, line, held, context) / (attr, line, held, what)
        self.writes: list[tuple[str, int, frozenset, str]] = []
        self.mutations: list[tuple[str, int, frozenset, str]] = []

    def _ctx(self) -> str:
        if self.nested:
            return f" in nested def `{self.nested[-1]}`"
        return ""

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            tok = _with_lock_token(item.context_expr, self.locks)
            if tok is not None:
                acquired.append(tok)
        self.held.extend(acquired)
        self.generic_visit(node)
        for _ in acquired:
            self.held.pop()

    def _record(self, target: ast.expr, line: int) -> None:
        attr = _is_self_attr(target)
        if attr is not None:
            self.writes.append((attr, line, frozenset(self.held), self._ctx()))
            return
        attr = _subscript_self_attr(target)
        if attr is not None and attr in self.containers:
            self.mutations.append((attr, line, frozenset(self.held),
                                   f"subscript write{self._ctx()}"))
            return
        if isinstance(target, ast.Subscript):
            name = _subscript_name(target)
            if name is not None and name in self.aliases:
                self.mutations.append((
                    self.aliases[name], line, frozenset(self.held),
                    f"subscript write via alias `{name}`{self._ctx()}"))
            elif name is not None and name in self.elem_aliases:
                self.mutations.append((
                    self.elem_aliases[name], line, frozenset(self.held),
                    f"subscript write via element `{name}` of "
                    f"self.{self.elem_aliases[name]}{self._ctx()}"))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            attr = _subscript_self_attr(t)
            if attr is not None and attr in self.containers:
                self.mutations.append((attr, node.lineno, frozenset(self.held),
                                       f"del{self._ctx()}"))
                continue
            if isinstance(t, ast.Subscript):
                name = _subscript_name(t)
                if name is not None and name in self.aliases:
                    self.mutations.append((
                        self.aliases[name], node.lineno, frozenset(self.held),
                        f"del via alias `{name}`{self._ctx()}"))
                elif name is not None and name in self.elem_aliases:
                    self.mutations.append((
                        self.elem_aliases[name], node.lineno,
                        frozenset(self.held),
                        f"del via element `{name}` of "
                        f"self.{self.elem_aliases[name]}{self._ctx()}"))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATOR_METHODS:
            attr = _is_self_attr(fn.value)
            if attr is not None and attr in self.containers:
                self.mutations.append(
                    (attr, node.lineno, frozenset(self.held),
                     f".{fn.attr}(){self._ctx()}")
                )
            elif (isinstance(fn.value, ast.Name)
                    and fn.value.id in self.aliases):
                self.mutations.append((
                    self.aliases[fn.value.id], node.lineno,
                    frozenset(self.held),
                    f".{fn.attr}() via alias `{fn.value.id}`{self._ctx()}"))
            elif (isinstance(fn.value, ast.Name)
                    and fn.value.id in self.elem_aliases):
                self.mutations.append((
                    self.elem_aliases[fn.value.id], node.lineno,
                    frozenset(self.held),
                    f".{fn.attr}() via element `{fn.value.id}` of "
                    f"self.{self.elem_aliases[fn.value.id]}{self._ctx()}"))
        else:
            hname = _callee_name(fn)
            if hname in HEAP_FUNCS and node.args:
                arg0 = node.args[0]
                attr = _is_self_attr(arg0)
                if attr is not None and attr in self.containers:
                    self.mutations.append(
                        (attr, node.lineno, frozenset(self.held),
                         f"{hname}(){self._ctx()}")
                    )
                elif (isinstance(arg0, ast.Name)
                        and arg0.id in self.aliases):
                    self.mutations.append((
                        self.aliases[arg0.id], node.lineno,
                        frozenset(self.held),
                        f"{hname}() via alias `{arg0.id}`{self._ctx()}"))
                elif (isinstance(arg0, ast.Name)
                        and arg0.id in self.elem_aliases):
                    self.mutations.append((
                        self.elem_aliases[arg0.id], node.lineno,
                        frozenset(self.held),
                        f"{hname}() via element `{arg0.id}` of "
                        f"self.{self.elem_aliases[arg0.id]}{self._ctx()}"))
        self.generic_visit(node)

    # nested defs (callbacks) mutate the SAME captured object — walk them,
    # reporting at the enclosing method, with the closure's own bindings
    # shadowing the enclosing aliases (ISSUE 10)
    def _visit_nested(self, node, name: str, params: set[str],
                      body) -> None:
        shadowed = params | _bound_names(node)
        saved = (self.aliases, self.elem_aliases)
        self.aliases = {k: v for k, v in self.aliases.items()
                        if k not in shadowed}
        self.elem_aliases = {k: v for k, v in self.elem_aliases.items()
                             if k not in shadowed}
        self.nested.append(name)
        try:
            if isinstance(body, list):
                for stmt in body:
                    self.visit(stmt)
            else:
                self.visit(body)
        finally:
            self.nested.pop()
            self.aliases, self.elem_aliases = saved

    def visit_FunctionDef(self, node) -> None:
        params = {a.arg for a in (node.args.args + node.args.kwonlyargs
                                  + node.args.posonlyargs)}
        if node.args.vararg is not None:
            params.add(node.args.vararg.arg)
        if node.args.kwarg is not None:
            params.add(node.args.kwarg.arg)
        self._visit_nested(node, node.name, params, node.body)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        params = {a.arg for a in (node.args.args + node.args.kwonlyargs
                                  + node.args.posonlyargs)}
        self._visit_nested(node, "<lambda>", params, node.body)


def _bound_names(fn) -> set[str]:
    """Every name a nested def (re)binds anywhere inside — used to shadow
    enclosing aliases conservatively (a rebound capture is not provably
    the container any more)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
    return out


def _lock_order_edges(
    table: dict, scans: dict[str, _HeldCallScanner]
) -> dict[tuple[str, str], tuple[str, str, int]]:
    """(lockA, lockB) -> (class, method, line) where A is held when B is
    acquired, expanding one level of self-calls.  Lock identities are
    TOKENS (own attrs or cross-object paths), so an inversion between
    ``self._mu`` and ``self.queue._cond`` is a cycle too."""
    method_acquires: dict[str, list[str]] = {}
    edges: dict[tuple[str, str], tuple[str, str, int]] = {}
    for meth, sc in scans.items():
        ci, _fn = table[meth]
        for tok, _line in sc.top_acquires:
            method_acquires.setdefault(meth, []).append(tok)
        for h, a, line in sc.edges:
            edges.setdefault((h, a), (ci.name, meth, line))
    # one level of call expansion: caller holds H, callee acquires A at top
    for meth, sc in scans.items():
        ci, _fn = table[meth]
        for callee, held, line in sc.calls:
            if not held:
                continue
            for a in method_acquires.get(callee, ()):
                for h in held:
                    if h != a:
                        edges.setdefault(
                            (h, a), (ci.name, f"{meth}->{callee}", line))
    return edges


def _find_cycles(edges: dict) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: list[list[str]] = []
    seen_cycles: set[frozenset] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(path + [start])
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return cycles


def _bound_method_aliases(
    table: dict, attr_types: dict[str, ClassInfo]
) -> dict[str, tuple[ClassInfo, str]]:
    """``self.f = self.<attr>.<m>`` where ``attr`` is attr-typed: ``f`` is
    a bound-method alias — a later ``self.f(...)`` call IS a call of the
    collaborator's ``m`` (the HPA shape:
    ``self.metrics = self.metrics_client.utilization``)."""
    out: dict[str, tuple[ClassInfo, str]] = {}
    for _meth, (_ci, fn) in table.items():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            path = _self_attr_path(node.value)
            if path is None or path.count(".") != 1:
                continue
            base, m = path.split(".")
            cls = attr_types.get(base)
            if cls is None:
                continue
            for t in node.targets:
                f = _is_self_attr(t)
                if f:
                    out.setdefault(f, (cls, m))
    return out


def _cross_object_entries(
    index: _ClassIndex, class_infos: list[ClassInfo]
) -> dict[int, dict[str, str]]:
    """One-hop cross-object reachability: for every class with its OWN
    thread entries, any worker-reachable call ``self.<attr>.<m>(...)`` —
    or a call through a bound-method alias of such a path — on an
    attr-typed collaborator marks ``m`` as an external thread entry of
    the collaborator class.  Returns ``id(collaborator ClassInfo) ->
    {method: "Caller.method"}`` (the via-label for messages).  One hop
    only: externally-entered classes do not themselves propagate."""
    out: dict[int, dict[str, str]] = {}
    method_tables: dict[int, dict] = {}
    for info in class_infos:
        entries = _thread_entries(index, info)
        if not entries:
            continue
        attr_types = _attr_types(index, info)
        if not attr_types:
            continue
        table = _method_table(index, info)
        bound = _bound_method_aliases(table, attr_types)
        for meth in sorted(_reachable(table, entries)):
            if meth == "__init__":
                continue
            _ci, fn = table[meth]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                target: Optional[tuple[ClassInfo, str]] = None
                path = _self_attr_path(node.func)
                if path is not None and path.count(".") == 1:
                    base, m = path.split(".")
                    cls = attr_types.get(base)
                    if cls is not None:
                        target = (cls, m)
                else:
                    f = _is_self_attr(node.func)
                    if f is not None and f in bound:
                        target = bound[f]
                if target is None:
                    continue
                cls, m = target
                tbl = method_tables.get(id(cls))
                if tbl is None:
                    tbl = method_tables[id(cls)] = _method_table(index, cls)
                if m not in tbl or m == "__init__":
                    continue
                out.setdefault(id(cls), {}).setdefault(
                    m, f"{info.name}.{meth}")
    return out


def run(root: str, paths: Optional[list[str]] = None) -> list[Finding]:
    files = iter_py_files(root, paths or DEFAULT_PATHS)
    index = _ClassIndex(files)
    findings: list[Finding] = list(index.parse_errors)
    reported: set[str] = set()

    class_infos = [
        info for key, info in sorted(index.classes.items()) if "::" in key
    ]
    ext_entries = _cross_object_entries(index, class_infos)
    for info in class_infos:
        table = _method_table(index, info)
        entries = _thread_entries(index, info)
        locks = _lock_attrs(index, info)
        tokens = _lock_tokens(index, info)
        ext = ext_entries.get(id(info), {})
        all_entries = sorted(set(entries) | set(ext))
        if not all_entries:
            if tokens:
                scans = _scan_methods(table, tokens)
                _report_lock_cycles(info, table, scans, findings, reported)
            continue
        # messages show where an external entry comes FROM: utilization
        # reached from HorizontalPodAutoscalerController.sync reads
        # `utilization<-HorizontalPodAutoscalerController.sync`
        entry_desc = "/".join(
            entries
            + [f"{m}<-{via}" for m, via in sorted(ext.items())
               if m not in entries]
        )
        containers = _container_attrs(index, info)
        reachable = _reachable(table, all_entries)
        scans = _scan_methods(table, tokens)
        entry_held = _entry_held(scans, all_entries, reachable)
        summaries = _return_summaries(
            table, index.module_funcs.get(info.path, {}))
        fns: dict[tuple, tuple] = {}
        for meth, (_ci, fn) in table.items():
            fns[("m", meth)] = (fn, True)
        for name, fn in index.module_funcs.get(info.path, {}).items():
            fns[("f", name)] = (fn, False)
        for meth in sorted(reachable):
            ci, fn = table[meth]
            if meth == "__init__":
                continue  # runs on the constructing (main) thread
            aliases, elem_aliases = _local_aliases(
                fn, containers, summaries=summaries, fns=fns)
            visitor = _WriteVisitor(tokens, containers, aliases=aliases,
                                    elem_aliases=elem_aliases)
            for stmt in fn.body:
                visitor.visit(stmt)
            at_entry = entry_held.get(meth, frozenset())
            for attr, line, held, ctx in visitor.writes:
                if attr in locks or held or at_entry:
                    continue
                # report at the DEFINING class so subclasses don't duplicate
                symbol = f"{ci.name}.{meth}.{attr}"
                key = f"RL301:{ci.path}:{symbol}"
                if key in reported:
                    continue
                reported.add(key)
                findings.append(
                    Finding(
                        code="RL301",
                        path=ci.path,
                        line=line,
                        symbol=symbol,
                        message=(
                            f"`self.{attr}` assigned{ctx} in worker-thread-"
                            f"reachable method `{meth}` (entry: "
                            f"{entry_desc}) without holding any of "
                            f"the object's locks "
                            f"({', '.join(sorted(tokens)) or 'none defined'})"
                        ),
                    )
                )
            for attr, line, held, what in visitor.mutations:
                if held or at_entry:
                    continue
                symbol = f"{ci.name}.{meth}.{attr}"
                key = f"RL303:{ci.path}:{symbol}"
                if key in reported:
                    continue
                reported.add(key)
                findings.append(
                    Finding(
                        code="RL303",
                        path=ci.path,
                        line=line,
                        symbol=symbol,
                        message=(
                            f"container `self.{attr}` mutated ({what}) in "
                            f"worker-thread-reachable method `{meth}` (entry: "
                            f"{entry_desc}) without holding any of the "
                            f"object's locks "
                            f"({', '.join(sorted(tokens)) or 'none defined'})"
                        ),
                    )
                )
        # lock-order cycles (per concrete class; report at defining site)
        _report_lock_cycles(info, table, scans, findings, reported)
    return findings


def _report_lock_cycles(
    info: ClassInfo,
    table: dict,
    scans: dict[str, _HeldCallScanner],
    findings: list[Finding],
    reported: set[str],
) -> None:
    edges = _lock_order_edges(table, scans)
    for cycle in _find_cycles(edges):
        a, b = cycle[0], cycle[1]
        cls, meth, line = edges[(a, b)]
        symbol = f"{cls}.lockcycle.{'-'.join(cycle[:-1])}"
        key = f"RL302:{info.path}:{symbol}"
        if key in reported:
            continue
        reported.add(key)
        findings.append(
            Finding(
                code="RL302",
                path=info.path,
                line=line,
                symbol=symbol,
                message=(
                    f"lock-acquisition-order cycle {' -> '.join(cycle)} "
                    f"(first edge in {cls}.{meth}): two threads taking these "
                    f"locks in opposite orders deadlock"
                ),
            )
        )
