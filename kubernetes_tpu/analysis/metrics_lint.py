"""Metrics-name lint (MN4xx): conventions for every metric the tree
registers (ISSUE 7 satellite).

Prometheus-style metrics are an API: dashboards, the SLO checks, and the
fault-matrix assertions all address them by NAME, so a misnamed metric
is a silent contract break.  The pass walks every scanned file for
constructions of the project's metric primitives (``Counter`` /
``Histogram`` / ``Gauge`` from ``utils.metrics``) with a literal name
and enforces:

- **MN401** — names are snake_case (``[a-z][a-z0-9_]*``): the Prometheus
  data model is case-sensitive and the exposition escapes nothing;
- **MN402** — counters end ``_total`` (the counter suffix convention the
  reference's metrics all follow);
- **MN403** — histograms carry a unit suffix (``_seconds`` /
  ``_microseconds`` / ``_milliseconds`` / ``_bytes`` / ``_fraction`` /
  ``_ratio``): a histogram without a unit cannot be read off a dashboard
  without source-diving;
- **MN404** — no duplicate registrations: the same literal name
  constructed at two different sites means two registries (or one
  registry twice) expose conflicting series under one name.
- **MN405** — every metric name an SLO spec reads (a ``RatioSLI`` /
  ``QuantileSLI`` construction, by position or by ``metric`` /
  ``bad_metric`` / ``total_metric`` / ``good_metric`` keyword) must
  resolve to a registration somewhere in the scanned set.  An SLI over a
  misspelled or deleted metric never sees data, and "no data" is
  deliberately never a breach — the burn-rate engine would go silently
  blind (ISSUE 13).

Only calls provably referring to the project's primitives count: the
file must import the name from a ``metrics`` module (or BE
``utils/metrics.py``), so ``collections.Counter`` never false-positives;
SLI constructions likewise require an import from an ``slo`` module (or
the file IS ``utils/slo.py``).
Symbols are the enclosing dotted scope plus the metric name — line-independent,
like every other pass (see ``core.Finding``).
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .core import Finding, iter_py_files

# the default scan scope: everywhere the runtime registers metrics
DEFAULT_PATHS = ["kubernetes_tpu"]

_METRIC_CLASSES = ("Counter", "Histogram", "Gauge")
_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_HIST_UNITS = ("_seconds", "_microseconds", "_milliseconds", "_bytes",
               "_fraction", "_ratio")

#: SLI spec classes and which of their arguments carry metric names:
#: positional slots by index, plus the keyword set (MN405)
_SLI_CLASSES: dict[str, tuple[tuple[int, ...], frozenset[str]]] = {
    "RatioSLI": ((0, 1), frozenset(
        {"bad_metric", "total_metric", "good_metric"})),
    "QuantileSLI": ((0,), frozenset({"metric"})),
    "GaugeSLI": ((0,), frozenset({"metric"})),
}


def _imported_metric_names(tree: ast.Module, rel_path: str) -> dict[str, str]:
    """name-in-this-file -> metric class, for names provably bound to the
    project's metric primitives.  ``utils/metrics.py`` itself defines
    them, so its bare names count."""
    out: dict[str, str] = {}
    if rel_path.replace("\\", "/").endswith("utils/metrics.py"):
        for cls in _METRIC_CLASSES:
            out[cls] = cls
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.split(".")[-1] != "metrics":
                continue
            for alias in node.names:
                if alias.name in _METRIC_CLASSES:
                    out[alias.asname or alias.name] = alias.name
    return out


def _imported_sli_names(tree: ast.Module, rel_path: str) -> dict[str, str]:
    """name-in-this-file -> SLI class, for names provably bound to the
    SLO layer's spec primitives (imported from an ``slo`` module, or the
    file IS ``utils/slo.py``)."""
    out: dict[str, str] = {}
    if rel_path.replace("\\", "/").endswith("utils/slo.py"):
        for cls in _SLI_CLASSES:
            out[cls] = cls
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.split(".")[-1] != "slo":
                continue
            for alias in node.names:
                if alias.name in _SLI_CLASSES:
                    out[alias.asname or alias.name] = alias.name
    return out


class _Scope(ast.NodeVisitor):
    """Collect metric constructions with their enclosing dotted scope."""

    def __init__(self, names: dict[str, str],
                 sli_names: Optional[dict[str, str]] = None):
        self._names = names
        self._sli_names = sli_names or {}
        self._stack: list[str] = []
        # (metric class, literal name, line, scope path)
        self.found: list[tuple[str, str, int, str]] = []
        # (SLI class, referenced metric name, line, scope path)
        self.sli_refs: list[tuple[str, str, int, str]] = []

    def _visit_scoped(self, node) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_scoped
    visit_AsyncFunctionDef = _visit_scoped
    visit_ClassDef = _visit_scoped

    def visit_Call(self, node: ast.Call) -> None:
        cls = None
        if isinstance(node.func, ast.Name):
            cls = self._names.get(node.func.id)
        elif isinstance(node.func, ast.Attribute):
            # metrics.Counter(...) through a module alias named *metrics*
            if (isinstance(node.func.value, ast.Name)
                    and node.func.value.id.endswith("metrics")
                    and node.func.attr in _METRIC_CLASSES):
                cls = node.func.attr
        if cls is not None and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                scope = ".".join(self._stack)
                self.found.append((cls, first.value, node.lineno, scope))
        if isinstance(node.func, ast.Name):
            sli = self._sli_names.get(node.func.id)
            if sli is not None:
                slots, kwset = _SLI_CLASSES[sli]
                scope = ".".join(self._stack)
                for i in slots:
                    if (i < len(node.args)
                            and isinstance(node.args[i], ast.Constant)
                            and isinstance(node.args[i].value, str)):
                        self.sli_refs.append(
                            (sli, node.args[i].value, node.lineno, scope))
                for kw in node.keywords:
                    if (kw.arg in kwset
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)):
                        self.sli_refs.append(
                            (sli, kw.value.value, node.lineno, scope))
        self.generic_visit(node)


def run(root: str, paths: Optional[list[str]] = None) -> list[Finding]:
    findings: list[Finding] = []
    registrations: list[tuple[str, str, int, str, str]] = []
    sli_refs: list[tuple[str, str, int, str, str]] = []
    for abs_path, rel_path in iter_py_files(root, paths or DEFAULT_PATHS):
        with open(abs_path, "r", encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=rel_path)
            except SyntaxError:
                continue
        names = _imported_metric_names(tree, rel_path)
        sli_names = _imported_sli_names(tree, rel_path)
        if not names and not sli_names:
            continue
        visitor = _Scope(names, sli_names)
        visitor.visit(tree)
        for sli, metric_name, line, scope in visitor.sli_refs:
            symbol = f"{scope}.{metric_name}" if scope else metric_name
            sli_refs.append((metric_name, rel_path, line, symbol, sli))
        for cls, metric_name, line, scope in visitor.found:
            symbol = f"{scope}.{metric_name}" if scope else metric_name
            registrations.append((metric_name, rel_path, line, symbol, cls))
            if not _SNAKE.match(metric_name):
                findings.append(Finding(
                    "MN401", rel_path, line, symbol,
                    f"metric name {metric_name!r} is not snake_case"))
            if cls == "Counter" and not metric_name.endswith("_total"):
                findings.append(Finding(
                    "MN402", rel_path, line, symbol,
                    f"counter {metric_name!r} does not end in '_total'"))
            if cls == "Histogram" and not metric_name.endswith(_HIST_UNITS):
                findings.append(Finding(
                    "MN403", rel_path, line, symbol,
                    f"histogram {metric_name!r} carries no unit suffix "
                    f"(expected one of {', '.join(_HIST_UNITS)})"))
    # MN404: the same literal name at two different construction sites —
    # deterministic order (path, line), the FIRST site is the canonical
    # registration and every later one is flagged
    by_name: dict[str, list] = {}
    for reg in sorted(registrations, key=lambda r: (r[1], r[2])):
        by_name.setdefault(reg[0], []).append(reg)
    for metric_name, regs in by_name.items():
        for name, rel_path, line, symbol, _cls in regs[1:]:
            first = regs[0]
            findings.append(Finding(
                "MN404", rel_path, line, symbol,
                f"duplicate registration of {metric_name!r} "
                f"(first registered at {first[1]}:{first[2]})"))
    # MN405: SLO specs must read metrics that exist — an SLI over an
    # unregistered name sees "no data" forever and (by design) no data is
    # never a breach, so the misconfiguration would be silent
    registered_names = {r[0] for r in registrations}
    for metric_name, rel_path, line, symbol, sli in sorted(
            sli_refs, key=lambda r: (r[1], r[2])):
        if metric_name in registered_names:
            continue
        findings.append(Finding(
            "MN405", rel_path, line, symbol,
            f"{sli} reads metric {metric_name!r} which is registered "
            f"nowhere in the scanned set — the SLO over it is "
            f"permanently blind"))
    return findings
