"""Device-contract lint (DC6xx): the accelerator disciplines the wave
path lives by — donation, host-sync budget, stable compiled shapes, and
clone-on-write snapshot hygiene — enforced structurally instead of by
comments and reviewer memory.

The pass scans ``ops/`` plus ``models/snapshot.py`` (the device seam)
and reuses the shapes proven out by the races/tracecov passes: lexical
annotations with mandatory reasons, name-level summaries propagated to
a fixed point, and over-approximation toward SILENCE — anything the
analysis cannot prove is dropped, never flagged.

Rules
-----
- **DC600** — a file in scope does not parse (same contract as RL300 /
  TC500).
- **DC601** — *use-after-donate*: a call through a jit wrapper built
  with ``donate_argnums`` (directly, or through a factory chain —
  ``_loop_runner`` → ``_loop_runner_for`` → ``self._loop``) consumes
  the donated actuals' buffers; any READ of a donated actual (a
  ``self.<attr>`` path or a local name) after the dispatch and at or
  before the next rebind — in the same function, or in a callee
  (same-class method / sibling nested def) invoked in that window — is
  a read of dead memory.
- **DC602** — *host-sync budget*: a host-materialization call
  (``.item()`` / ``.tolist()`` / ``float()``/``int()``/``bool()`` on a
  device-tainted value, ``np.asarray``/``np.array`` of one,
  ``jax.device_get``, ``.block_until_ready()``) inside a wave-hot-path
  module must sit at a site annotated ``# device: sync — <reason>``
  (same line or the line above).  ``.copy_to_host_async()`` is not a
  sync.  :func:`sanctioned_sync_sites` counts the sanctioned sites per
  function so the PR-11 O(compactions + 1) budget is auditable — and a
  tier-1 test holds the runtime ``host_syncs`` stat to the static
  count.
- **DC603** — *recompile guard*: shape-bearing expressions flowing into
  compiled-program identity must route through the sticky-bucket
  helpers or carry a ``# device: static`` annotation: (a) a
  ``_pad_to(...)`` call outside a ``_sticky_pad``/``_bucket`` wrapper,
  (b) a ``_pow2_width(...)`` call (each distinct width is its own
  executable — the annotation records the accepted ≤ log2(N) compile
  budget), (c) an argument at a compile-keyed factory boundary (an
  ``lru_cache``-decorated function returning a jitted callable) that is
  not a normalized scalar (``int()``/``bool()``/``tuple()``/constant/
  bool- or int-annotated parameter).
- **DC604** — *CoW snapshot writes*: in any scanned function that
  receives the scheduler snapshot (a ``node_info_map`` parameter, or a
  ``dict(node_info_map)`` working copy), mutating a ``NodeInfo``
  obtained from that map (``.add_pod`` / ``.add_pod_counted`` /
  ``.remove_pod`` / ``.replace_pod`` / ``.set_node`` / ``.remove_node``,
  or an attribute store) without flowing through ``mutable_info`` is an
  error — the ROADMAP's "must route through mutable_info" caveat,
  gated.
- **DC605** — a stale or reasonless device annotation: a
  ``# device: sync`` with no materialization-shaped call on its line or
  the next (the check is LEXICAL so an annotation stays valid even
  where the taint under-approximates), a sync annotation with no
  reason, or a ``# device: static`` sanctioning no shape site.

Deliberately NOT modeled (over-approximating toward silence): donation
through containers or across instance-method boundaries (only the
rebind window inside the dispatching function plus one callee hop);
taint through functions defined outside the scanned module (a value
returned by an unscanned helper is host until proven device); CoW
aliasing through collaborator objects (``PriorityContext(work_map)``)
— the map handed to a constructor is trusted read-only.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .core import Finding, iter_py_files
from .tracecov import HOT_PATH_MODULES

DEFAULT_PATHS = [
    "kubernetes_tpu/ops",
    "kubernetes_tpu/models/snapshot.py",
    "kubernetes_tpu/parallel",
]

#: NodeInfo's mutating surface (scheduler/nodeinfo.py); ``clone()`` is
#: deliberately absent — cloning IS the sanctioned CoW step.
NODEINFO_MUTATORS = {
    "add_pod", "add_pod_counted", "remove_pod", "replace_pod",
    "set_node", "remove_node",
}

#: array metadata — reading these never materializes device memory
_METADATA_ATTRS = {"shape", "ndim", "size", "dtype", "nbytes"}

#: module roots whose calls produce device values
_DEVICE_ROOTS = {"jnp", "lax"}

_SYNC_ANN_RE = re.compile(
    r"#\s*device:\s*sync\s*(?:—|–|-{1,2})?\s*(.*)$")
_STATIC_ANN_RE = re.compile(r"#\s*device:\s*static\b")
#: lexical materialization shapes for the DC605 stale-sync check — kept
#: looser than the AST forms so a sanctioned site the taint misses does
#: not round-trip into a stale-annotation finding
_SYNC_LEXEME_RE = re.compile(
    r"\.item\(|\.tolist\(|\bint\(|\bfloat\(|\bbool\(|np\.asarray\(|"
    r"np\.array\(|device_get\(|block_until_ready\(")


class _Func:
    __slots__ = ("node", "qualname", "name", "parent")

    def __init__(self, node, qualname: str, parent: "Optional[_Func]"):
        self.node = node
        self.qualname = qualname
        self.name = node.name
        self.parent = parent  # enclosing _Func, None at module/class level


def _collect_funcs(tree: ast.Module) -> list[_Func]:
    out: list[_Func] = []

    def visit(node, prefix: str, parent: Optional[_Func]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                f = _Func(child, qual, parent)
                out.append(f)
                visit(child, qual, f)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}" if prefix
                      else child.name, parent)
            else:
                visit(child, prefix, parent)

    visit(tree, "", None)
    return out


def _enclosing(funcs: list[_Func], line: int) -> Optional[_Func]:
    best: Optional[_Func] = None
    for f in funcs:
        if f.node.lineno <= line <= (f.node.end_lineno or f.node.lineno):
            if best is None or f.node.lineno > best.node.lineno:
                best = f
    return best


def _attr_root(node: ast.expr) -> Optional[str]:
    """``jnp.sum`` / ``jax.lax.scan`` -> the base Name ("jnp"/"jax")."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _self_attr(node: ast.expr) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _callee_attr_name(call: ast.Call) -> Optional[str]:
    """The method name of ``X.m(...)``; None for bare calls."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _own_returns(fn: ast.FunctionDef) -> list[ast.Return]:
    """Return statements owned by ``fn`` itself, nested defs excluded."""
    out: list[ast.Return] = []

    def walk(node) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Return):
                out.append(child)
            walk(child)

    walk(fn)
    return out


def _own_statements(fn: ast.FunctionDef):
    """Statement-level nodes owned by ``fn``, nested def bodies excluded."""
    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from walk(child)

    yield from walk(fn)


def _donate_from_keywords(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                idx = tuple(e.value for e in v.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int))
                return idx
    return ()


def _is_jax_jit(expr: ast.expr) -> bool:
    return (isinstance(expr, ast.Attribute) and expr.attr == "jit"
            and isinstance(expr.value, ast.Name) and expr.value.id == "jax")


def _decorated_jit(fn: ast.FunctionDef) -> Optional[tuple[int, ...]]:
    """Donation of an ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorator,
    or None when the function is not jit-decorated."""
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return ()
        if (isinstance(dec, ast.Call) and _is_jax_jit(dec.func)):
            return _donate_from_keywords(dec)
        if (isinstance(dec, ast.Call) and isinstance(dec.func, ast.Name)
                and dec.func.id == "partial" and dec.args
                and _is_jax_jit(dec.args[0])):
            return _donate_from_keywords(dec)
    return None


def _has_lru_cache(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(name, ast.Name) and name.id == "lru_cache":
            return True
        if isinstance(name, ast.Attribute) and name.attr == "lru_cache":
            return True
    return False


class _ModuleIndex:
    """Per-module summaries: jit factories (+ donation), compile-keyed
    factory names, device-returning module functions, class attribute
    taint, and per-function local environments."""

    def __init__(self, tree: ast.Module, funcs: list[_Func]):
        self.tree = tree
        self.funcs = funcs
        self.top_fns: dict[str, ast.FunctionDef] = {}
        self.classes: dict[str, ast.ClassDef] = {}
        for child in ast.iter_child_nodes(tree):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.top_fns[child.name] = child
            elif isinstance(child, ast.ClassDef):
                self.classes[child.name] = child
        # name -> donate indices of the callable the factory returns
        self.factories: dict[str, tuple[int, ...]] = {}
        self.compile_keyed: set[str] = set()
        self.device_fns: set[str] = set()
        # class name -> (device attrs, callable attrs -> donate)
        self.cls_attrs: dict[str, set[str]] = {}
        self.cls_callables: dict[str, dict[str, tuple[int, ...]]] = {}
        self._build_factories()
        self._build_device_summaries()

    # -- jit factories ------------------------------------------------------

    def _build_factories(self) -> None:
        changed = True
        while changed:
            changed = False
            for name, fn in self.top_fns.items():
                if name in self.factories:
                    continue
                donate = self._factory_donate(fn)
                if donate is not None:
                    self.factories[name] = donate
                    if _has_lru_cache(fn):
                        self.compile_keyed.add(name)
                    changed = True

    def _factory_donate(self, fn: ast.FunctionDef) -> Optional[tuple[int, ...]]:
        nested = {c.name: c for c in ast.iter_child_nodes(fn)
                  if isinstance(c, ast.FunctionDef)}
        for ret in _own_returns(fn):
            v = ret.value
            if v is None:
                continue
            if isinstance(v, ast.Call) and _is_jax_jit(v.func):
                return _donate_from_keywords(v)
            if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                    and v.func.id in self.factories):
                return self.factories[v.func.id]
            if isinstance(v, ast.Name) and v.id in nested:
                donate = _decorated_jit(nested[v.id])
                if donate is not None:
                    return donate
        return None

    # -- device-value summaries --------------------------------------------

    def _build_device_summaries(self) -> None:
        for _round in range(3):  # module fns x class attrs to a fixed point
            before = (len(self.device_fns),
                      sum(len(s) for s in self.cls_attrs.values()),
                      sum(len(s) for s in self.cls_callables.values()))
            for name, fn in self.top_fns.items():
                if name in self.factories or name in self.device_fns:
                    continue
                env = self.local_env(fn, cls=None)
                returns = _own_returns(fn)
                if returns and all(
                        r.value is not None
                        and self.expr_is_device(r.value, env)
                        for r in returns):
                    self.device_fns.add(name)
            for cname, cls in self.classes.items():
                attrs = self.cls_attrs.setdefault(cname, set())
                callables = self.cls_callables.setdefault(cname, {})
                for item in ast.walk(cls):
                    if not isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    env = self.local_env(item, cls=cname)
                    for stmt in _own_statements(item):
                        self._class_taint_stmt(stmt, env, attrs, callables)
            after = (len(self.device_fns),
                     sum(len(s) for s in self.cls_attrs.values()),
                     sum(len(s) for s in self.cls_callables.values()))
            if after == before:
                break

    def _class_taint_stmt(self, stmt, env, attrs: set[str],
                          callables: dict[str, tuple[int, ...]]) -> None:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._class_taint_pair(t, stmt.value, env, attrs, callables)
        elif isinstance(stmt, ast.AugAssign):
            a = _self_attr(stmt.target)
            if a is not None and self.expr_is_device(stmt.value, env):
                attrs.add(a)
        elif isinstance(stmt, ast.Call):
            # self.X.append(device-ish) taints the container attr
            if (isinstance(stmt.func, ast.Attribute)
                    and stmt.func.attr in ("append", "extend")
                    and stmt.args):
                a = _self_attr(stmt.func.value)
                if a is not None and self._any_device(stmt.args[0], env):
                    attrs.add(a)

    def _class_taint_pair(self, target, value, env, attrs, callables) -> None:
        a = _self_attr(target)
        if a is not None:
            donate = self.callable_donate(value, env)
            if donate is not None:
                callables[a] = donate
            elif self.expr_is_device(value, env):
                attrs.add(a)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            if (isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(target.elts)):
                for t, v in zip(target.elts, value.elts):
                    self._class_taint_pair(t, v, env, attrs, callables)
            elif self.expr_is_device(value, env):
                for t in target.elts:
                    at = _self_attr(t)
                    if at is not None:
                        attrs.add(at)

    # -- environments -------------------------------------------------------

    def local_env(self, fn, cls: Optional[str]):
        """(tainted locals, callable locals -> donate, class name) for
        ``fn``, flow-insensitive, two sweeps for ordering independence."""
        tainted: set[str] = set()
        callables: dict[str, tuple[int, ...]] = {}
        env = (tainted, callables, cls)
        for _sweep in range(2):
            for stmt in _own_statements(fn):
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        self._env_pair(t, stmt.value, env)
                elif isinstance(stmt, ast.AugAssign):
                    if (isinstance(stmt.target, ast.Name)
                            and self.expr_is_device(stmt.value, env)):
                        tainted.add(stmt.target.id)
                elif isinstance(stmt, ast.For):
                    if self.expr_is_device(stmt.iter, env):
                        for n in ast.walk(stmt.target):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
                elif isinstance(stmt, ast.Call):
                    if (isinstance(stmt.func, ast.Attribute)
                            and stmt.func.attr in ("append", "extend")
                            and stmt.args
                            and isinstance(stmt.func.value, ast.Name)
                            and self._any_device(stmt.args[0], env)):
                        tainted.add(stmt.func.value.id)
        return env

    def _env_pair(self, target, value, env) -> None:
        tainted, callables, _cls = env
        if isinstance(target, ast.Name):
            donate = self.callable_donate(value, env)
            if donate is not None:
                callables[target.id] = donate
            elif self.expr_is_device(value, env):
                tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if (isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(target.elts)):
                for t, v in zip(target.elts, value.elts):
                    self._env_pair(t, v, env)
            elif self.expr_is_device(value, env):
                for n in target.elts:
                    if isinstance(n, ast.Name):
                        tainted.add(n.id)

    # -- expression classification ------------------------------------------

    def callable_donate(self, expr, env) -> Optional[tuple[int, ...]]:
        """Donate indices when ``expr`` evaluates to a jit-compiled
        callable (factory call / ``jax.jit(...)``); None otherwise."""
        _tainted, callables, cls = env
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name):
                if expr.func.id in self.factories:
                    return self.factories[expr.func.id]
            if _is_jax_jit(expr.func):
                return _donate_from_keywords(expr)
        elif isinstance(expr, ast.Name) and expr.id in callables:
            return callables[expr.id]
        else:
            a = _self_attr(expr)
            if a is not None and cls is not None:
                got = self.cls_callables.get(cls, {}).get(a)
                if got is not None:
                    return got
        return None

    def _any_device(self, expr, env) -> bool:
        """ANY-part device — used only for container taint, where a tuple
        holding one device array makes the container device-bearing."""
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._any_device(e, env) for e in expr.elts)
        return self.expr_is_device(expr, env)

    def expr_is_device(self, expr, env) -> bool:
        tainted, callables, cls = env
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in _METADATA_ATTRS:
                return False
            a = _self_attr(expr)
            if a is not None:
                return cls is not None and a in self.cls_attrs.get(cls, set())
            return self.expr_is_device(expr.value, env)
        if isinstance(expr, ast.Subscript):
            return self.expr_is_device(expr.value, env)
        if isinstance(expr, ast.Starred):
            return self.expr_is_device(expr.value, env)
        if isinstance(expr, ast.Call):
            root = _attr_root(expr.func)
            if root in _DEVICE_ROOTS:
                return True
            if root == "jax":
                # jax.jit -> callable, jax.profiler.* -> context manager,
                # jax.device_get -> HOST by definition
                if isinstance(expr.func, ast.Attribute) and expr.func.attr in (
                        "jit", "device_get"):
                    return False
                if (isinstance(expr.func, ast.Attribute)
                        and isinstance(expr.func.value, ast.Attribute)
                        and expr.func.value.attr == "profiler"):
                    return False
                return True
            if isinstance(expr.func, ast.Name):
                if expr.func.id in self.device_fns:
                    return True
                if expr.func.id in callables:
                    return True
                if (expr.func.id[:1].isupper()
                        and any(self._any_device(a, env) for a in expr.args)
                        or expr.func.id[:1].isupper()
                        and any(kw.value is not None
                                and self._any_device(kw.value, env)
                                for kw in expr.keywords)):
                    # pytree constructor (ScanState/StaticArrays) over
                    # device leaves
                    return True
            if isinstance(expr.func, ast.Attribute):
                if expr.func.attr == "_replace" and self.expr_is_device(
                        expr.func.value, env):
                    return True
                a = _self_attr(expr.func)
                if a is not None and cls is not None \
                        and a in self.cls_callables.get(cls, {}):
                    return True
            return False
        if isinstance(expr, (ast.Tuple, ast.List)):
            return bool(expr.elts) and all(
                self.expr_is_device(e, env) for e in expr.elts)
        if isinstance(expr, ast.BinOp):
            return (self.expr_is_device(expr.left, env)
                    or self.expr_is_device(expr.right, env))
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_is_device(v, env) for v in expr.values)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_is_device(expr.operand, env)
        if isinstance(expr, ast.Compare):
            return (self.expr_is_device(expr.left, env)
                    or any(self.expr_is_device(c, env)
                           for c in expr.comparators))
        if isinstance(expr, ast.IfExp):
            return (self.expr_is_device(expr.body, env)
                    and self.expr_is_device(expr.orelse, env))
        return False


# -- annotations ------------------------------------------------------------


def _scan_annotations(src_lines: list[str]):
    """(sync annotations: line -> reason-or-None, static annotation
    lines).  Lines are 1-based."""
    sync: dict[int, Optional[str]] = {}
    static: set[int] = set()
    for i, line in enumerate(src_lines, start=1):
        m = _SYNC_ANN_RE.search(line)
        if m:
            reason = (m.group(1) or "").strip()
            sync[i] = reason or None
        elif _STATIC_ANN_RE.search(line):
            static.add(i)
    return sync, static


def _sync_sanctioned(sync_ann: dict[int, Optional[str]], line: int) -> bool:
    """A site is sanctioned by a reasoned sync annotation on its own line
    or the line above."""
    return bool(sync_ann.get(line) or sync_ann.get(line - 1))


def _static_sanctioned(static_ann: set[int], line: int) -> bool:
    return line in static_ann or (line - 1) in static_ann


def _materialization(call: ast.Call):
    """(operand expr, form label) when ``call`` is a host-materialization
    shape; None otherwise."""
    if isinstance(call.func, ast.Name):
        if call.func.id in ("int", "float", "bool") and len(call.args) == 1:
            return call.args[0], call.func.id
        return None
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in ("item", "tolist", "block_until_ready") and not call.args:
            return call.func.value, attr
        root = _attr_root(call.func)
        if root == "np" and attr in ("asarray", "array") and call.args:
            return call.args[0], f"np.{attr}"
        if root == "jax" and attr == "device_get" and call.args:
            return call.args[0], "device_get"
    return None


def _expr_label(expr: ast.expr) -> Optional[str]:
    """A stable dotted label for a simple operand (``self._state.round_robin``
    -> ``_state.round_robin``); None for complex expressions."""
    parts: list[str] = []
    while True:
        if isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        elif isinstance(expr, ast.Subscript):
            expr = expr.value
        elif isinstance(expr, ast.Call):
            # jnp.sum(x) -> label through the call's first operand
            if expr.args:
                expr = expr.args[0]
            else:
                return None
        elif isinstance(expr, ast.Name):
            if expr.id != "self":
                parts.append(expr.id)
            return ".".join(reversed(parts)) if parts else None
        else:
            return None


# -- the pass ---------------------------------------------------------------


def _analyze_file(rel: str, tree: ast.Module, src_lines: list[str],
                  hot: set[str]) -> list[Finding]:
    findings: list[Finding] = []
    funcs = _collect_funcs(tree)
    idx = _ModuleIndex(tree, funcs)
    sync_ann, static_ann = _scan_annotations(src_lines)
    # functions whose bodies are TRACED (inside a jit factory or directly
    # jit-decorated): host-materialization there is trace-safety's beat
    # (TS101), not a sync-budget question
    traced: set[int] = set()
    for f in funcs:
        if isinstance(f.node, ast.FunctionDef) \
                and _decorated_jit(f.node) is not None:
            traced.add(id(f))
        p = f.parent
        while p is not None:
            if p.name in idx.factories or id(p) in traced:
                traced.add(id(f))
                break
            p = p.parent

    def cls_of(f: _Func) -> Optional[str]:
        parts = f.qualname.split(".")
        return parts[0] if parts[0] in idx.classes else None

    env_cache: dict[int, tuple] = {}

    def env_of(f: _Func):
        got = env_cache.get(id(f))
        if got is None:
            got = idx.local_env(f.node, cls=cls_of(f))
            # closure visibility: merge the enclosing chain's taint so a
            # nested def reading an outer device local stays modeled
            p = f.parent
            while p is not None:
                pt, pc, _ = env_of(p)
                got[0].update(pt)
                got[1].update(pc)
                p = p.parent
            env_cache[id(f)] = got
        return got

    _dc601(rel, findings, funcs, idx, env_of, cls_of)
    if rel in hot:
        _dc602(rel, findings, funcs, idx, env_of, traced, sync_ann)
    used_static = _dc603(rel, findings, funcs, idx, env_of, static_ann)
    _dc604(rel, findings, funcs, idx)
    _dc605(rel, findings, funcs, src_lines, sync_ann, static_ann, used_static)
    return findings


def _dc601(rel, findings, funcs, idx, env_of, cls_of) -> None:
    for f in funcs:
        env = env_of(f)
        _tainted, callables, _cls = env
        cname = cls_of(f)
        for node in ast.walk(f.node):
            if not isinstance(node, ast.Call):
                continue
            donate: tuple[int, ...] = ()
            callee_desc = None
            if isinstance(node.func, ast.Name) \
                    and node.func.id in callables:
                donate = callables[node.func.id]
                callee_desc = node.func.id
            else:
                a = _self_attr(node.func)
                if a is not None and cname is not None:
                    donate = idx.cls_callables.get(cname, {}).get(a, ())
                    callee_desc = f"self.{a}"
            if not donate:
                continue
            enc = _enclosing(funcs, node.lineno)
            if enc is None or enc.node is not f.node:
                continue  # the innermost owner reports it, once
            for di in donate:
                if di >= len(node.args):
                    continue
                actual = node.args[di]
                path = _donated_path(actual)
                if path is None:
                    continue
                _check_donated_use(rel, findings, funcs, idx, f, node,
                                   path, di, callee_desc)


def _donated_path(expr: ast.expr):
    a = _self_attr(expr)
    if a is not None:
        return ("self", a)
    if isinstance(expr, ast.Name):
        return ("name", expr.id)
    return None


def _path_loads(tree_node, path, lo: int, hi: int) -> list[int]:
    kind, name = path
    out = []
    for n in ast.walk(tree_node):
        if not (lo < n.lineno <= hi if hasattr(n, "lineno") else False):
            continue
        if kind == "self":
            if (_self_attr(n) == name and isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, ast.Load)):
                out.append(n.lineno)
        else:
            if (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)):
                out.append(n.lineno)
    return out


def _path_stores(tree_node, path, lo: int) -> list[int]:
    kind, name = path
    out = []
    for n in ast.walk(tree_node):
        if not hasattr(n, "lineno") or n.lineno <= lo:
            continue
        if kind == "self":
            if (_self_attr(n) == name and isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, ast.Store)):
                out.append(n.lineno)
        else:
            if (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Store)):
                out.append(n.lineno)
    return out


def _check_donated_use(rel, findings, funcs, idx, f, call, path, di,
                       callee_desc) -> None:
    kind, name = path
    call_end = call.end_lineno or call.lineno
    fn_end = f.node.end_lineno or f.node.lineno
    stores = _path_stores(f.node, path, call_end)
    rebind = min(stores) if stores else fn_end + 1
    window_hi = min(rebind, fn_end)
    label = f"self.{name}" if kind == "self" else name
    loads = _path_loads(f.node, path, call_end, window_hi)
    for ln in loads:
        findings.append(Finding(
            code="DC601", path=rel, line=ln,
            symbol=f"{f.qualname}.{name}",
            message=(
                f"use after donate: `{label}` was donated (arg {di} of "
                f"`{callee_desc}(...)`, line {call.lineno}) — its buffer "
                f"is dead the moment the dispatch returns, but it is read "
                f"here before the next rebind; rebind from the call's "
                f"outputs first"
            ),
        ))
    if kind != "self":
        return
    # one callee hop: a method/nested-def invoked inside the window that
    # reads the donated attribute is the same bug, one frame down
    cls_name = f.qualname.split(".")[0]
    methods = {m.name: m for m in funcs
               if m.qualname.startswith(cls_name + ".")
               and m.node is not f.node}
    for n in ast.walk(f.node):
        if not isinstance(n, ast.Call) or not hasattr(n, "lineno"):
            continue
        if not (call_end < n.lineno <= window_hi):
            continue
        m = _self_attr(n.func)
        if m is None and isinstance(n.func, ast.Name):
            m = n.func.id
        callee = methods.get(m) if m else None
        if callee is None:
            continue
        if _path_loads(callee.node, path, 0, 10 ** 9):
            findings.append(Finding(
                code="DC601", path=rel, line=n.lineno,
                symbol=f"{f.qualname}.{name}.{callee.name}",
                message=(
                    f"use after donate: `{callee.qualname}` (called here, "
                    f"before `{label}` is rebound) reads `{label}`, whose "
                    f"buffer was donated at line {call.lineno}"
                ),
            ))


def _dc602(rel, findings, funcs, idx, env_of, traced, sync_ann) -> None:
    for f in funcs:
        if id(f) in traced:
            continue
        env = env_of(f)
        for node in ast.walk(f.node):
            if not isinstance(node, ast.Call):
                continue
            enc = _enclosing(funcs, node.lineno)
            if enc is None or enc.node is not f.node:
                continue
            got = _materialization(node)
            if got is None:
                continue
            operand, form = got
            if not idx.expr_is_device(operand, env):
                continue
            if _sync_sanctioned(sync_ann, node.lineno):
                continue
            label = _expr_label(operand) or form
            findings.append(Finding(
                code="DC602", path=rel, line=node.lineno,
                symbol=f"{f.qualname}.{label}",
                message=(
                    f"host sync outside the budget: `{form}` materializes "
                    f"a device value in wave-hot-path function "
                    f"`{f.qualname}` with no `# device: sync — <reason>` "
                    f"annotation — every blocking device→host round-trip "
                    f"on this path must be a declared, counted site"
                ),
            ))


def _dc603(rel, findings, funcs, idx, env_of, static_ann) -> set[int]:
    """Returns the annotation lines actually consumed (for DC605)."""
    used: set[int] = set()

    def consume(line: int) -> bool:
        hit = False
        for ln in (line, line - 1):
            if ln in static_ann:
                used.add(ln)
                hit = True
        return hit

    # _pad_to calls nested under a sticky wrapper are sanctioned
    sticky_wrapped: set[int] = set()
    for node in ast.walk(idx.tree):
        if isinstance(node, ast.Call):
            cal = _callee_attr_name(node) or (
                node.func.id if isinstance(node.func, ast.Name) else None)
            if cal in ("_sticky_pad", "_bucket"):
                for sub in ast.walk(node):
                    if sub is not node and isinstance(sub, ast.Call):
                        sticky_wrapped.add(id(sub))

    for node in ast.walk(idx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = node.func.id if isinstance(node.func, ast.Name) else None
        enc = _enclosing(funcs, node.lineno)
        qual = enc.qualname if enc is not None else "<module>"
        if name == "_pad_to":
            if id(node) in sticky_wrapped:
                continue
            if enc is not None and enc.name in ("_pad_to", "_sticky_pad",
                                                "_bucket"):
                continue
            if consume(node.lineno):
                continue
            findings.append(Finding(
                code="DC603", path=rel, line=node.lineno,
                symbol=f"{qual}._pad_to",
                message=(
                    "shape-bearing pad outside the sticky buckets: a bare "
                    "`_pad_to(...)` result that reaches the device keys a "
                    "fresh XLA compile every time it moves — route it "
                    "through `_sticky_pad`/`_bucket`, or annotate the site "
                    "`# device: static` with the stability argument"
                ),
            ))
        elif name == "_pow2_width":
            if enc is not None and enc.name == "_pow2_width":
                continue
            if consume(node.lineno):
                continue
            findings.append(Finding(
                code="DC603", path=rel, line=node.lineno,
                symbol=f"{qual}._pow2_width",
                message=(
                    "shape-bearing width at a jit boundary: each distinct "
                    "`_pow2_width(...)` result is its own compiled "
                    "executable — annotate the site `# device: static` to "
                    "declare the accepted <= log2(N) compile budget"
                ),
            ))
        elif name in idx.compile_keyed:
            if consume(node.lineno):
                continue  # one annotation sanctions the whole boundary
            if enc is None:
                continue
            for i, arg in enumerate(list(node.args)
                                    + [kw.value for kw in node.keywords]):
                if _compile_key_ok(arg, enc.node, idx):
                    continue
                if consume(arg.lineno):
                    continue
                desc = _expr_label(arg) or f"arg{i}"
                findings.append(Finding(
                    code="DC603", path=rel, line=arg.lineno,
                    symbol=f"{qual}.{name}.{desc}",
                    message=(
                        f"un-normalized compile key: argument `{desc}` of "
                        f"compile-keyed factory `{name}(...)` is not a "
                        f"normalized scalar (`int()`/`bool()`/`tuple()`/"
                        f"constant/typed parameter) — a drifting value "
                        f"here recompiles per distinct value; normalize "
                        f"it or annotate the call `# device: static`"
                    ),
                ))
    return used


def _compile_key_ok(arg: ast.expr, enc_fn, idx: _ModuleIndex) -> bool:
    if isinstance(arg, ast.Constant):
        return True
    if isinstance(arg, ast.Call):
        if isinstance(arg.func, ast.Name):
            if arg.func.id in ("int", "bool", "float", "tuple", "str", "len"):
                return True
            callee = idx.top_fns.get(arg.func.id)
            if callee is not None and isinstance(callee.returns, ast.Name) \
                    and callee.returns.id in ("int", "bool", "str", "float"):
                return True
        return False
    if isinstance(arg, ast.Name):
        # bool/int-annotated parameter of the enclosing function
        for a in (enc_fn.args.args + enc_fn.args.kwonlyargs
                  + enc_fn.args.posonlyargs):
            if a.arg == arg.id:
                return (isinstance(a.annotation, ast.Name)
                        and a.annotation.id in ("bool", "int", "str",
                                                "float", "tuple"))
        # local single-assigned to an ok value
        assigns = [s for s in _own_statements(enc_fn)
                   if isinstance(s, ast.Assign)
                   and any(isinstance(t, ast.Name) and t.id == arg.id
                           for t in s.targets)]
        if len(assigns) == 1:
            return _compile_key_ok(assigns[0].value, enc_fn, idx)
    return False


def _dc604(rel, findings, funcs, idx) -> None:
    for f in funcs:
        if f.parent is not None:
            continue  # analyze each outermost function's whole subtree
        roots: set[str] = set()
        for g in funcs:
            if g is not f and not g.qualname.startswith(f.qualname + "."):
                continue
            for a in (g.node.args.args + g.node.args.kwonlyargs
                      + g.node.args.posonlyargs):
                if a.arg == "node_info_map":
                    roots.add(a.arg)
        # working copies: w = dict(root) / w = root
        changed = True
        while changed:
            changed = False
            for node in ast.walk(f.node):
                if not isinstance(node, ast.Assign):
                    continue
                v = node.value
                src = None
                if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                        and v.func.id == "dict" and len(v.args) == 1
                        and isinstance(v.args[0], ast.Name)):
                    src = v.args[0].id
                elif isinstance(v, ast.Name):
                    src = v.id
                if src in roots:
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id not in roots:
                            roots.add(t.id)
                            changed = True
        if not roots:
            continue

        def from_root(expr) -> bool:
            """``root[k]`` / ``root.get(k)`` — a NodeInfo straight off the
            snapshot map."""
            if isinstance(expr, ast.Subscript):
                return (isinstance(expr.value, ast.Name)
                        and expr.value.id in roots)
            if (isinstance(expr, ast.Call)
                    and isinstance(expr.func, ast.Attribute)
                    and expr.func.attr == "get"
                    and isinstance(expr.func.value, ast.Name)):
                return expr.func.value.id in roots
            return False

        snapshot_names: set[str] = set()
        sanctioned_names: set[str] = set()
        for node in ast.walk(f.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    v = node.value
                    if from_root(v):
                        snapshot_names.add(t.id)
                    elif (isinstance(v, ast.Call)
                          and ((isinstance(v.func, ast.Name)
                                and v.func.id == "mutable_info")
                               or _callee_attr_name(v) == "mutable_info")):
                        sanctioned_names.add(t.id)
            elif isinstance(node, ast.For):
                # for name, info in root.items() / for info in root.values()
                it = node.iter
                if (isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Attribute)
                        and isinstance(it.func.value, ast.Name)
                        and it.func.value.id in roots):
                    if (it.func.attr == "items"
                            and isinstance(node.target, ast.Tuple)
                            and len(node.target.elts) == 2
                            and isinstance(node.target.elts[1], ast.Name)):
                        snapshot_names.add(node.target.elts[1].id)
                    elif (it.func.attr == "values"
                          and isinstance(node.target, ast.Name)):
                        snapshot_names.add(node.target.id)
        # a name ever sanctioned wins (over-approximate toward silence)
        snapshot_only = snapshot_names - sanctioned_names

        for node in ast.walk(f.node):
            enc = _enclosing(funcs, getattr(node, "lineno", 0)) if hasattr(
                node, "lineno") else None
            qual = enc.qualname if enc is not None else f.qualname
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Attribute) \
                    and node.func.attr in NODEINFO_MUTATORS:
                recv = node.func.value
                label = None
                if isinstance(recv, ast.Name) and recv.id in snapshot_only:
                    label = recv.id
                elif from_root(recv):
                    label = _expr_label(recv) or "<snapshot>"
                if label is not None:
                    findings.append(Finding(
                        code="DC604", path=rel, line=node.lineno,
                        symbol=f"{qual}.{label}.{node.func.attr}",
                        message=(
                            f"snapshot write bypasses clone-on-write: "
                            f"`.{node.func.attr}(...)` mutates a NodeInfo "
                            f"taken straight from the snapshot map — it "
                            f"corrupts the scheduler cache's CoW snapshot; "
                            f"obtain the target via `mutable_info(...)` "
                            f"so the first write clones"
                        ),
                    ))
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in snapshot_only):
                        findings.append(Finding(
                            code="DC604", path=rel, line=node.lineno,
                            symbol=f"{qual}.{t.value.id}.{t.attr}",
                            message=(
                                f"snapshot write bypasses clone-on-write: "
                                f"attribute store `{t.value.id}.{t.attr} ="
                                f" ...` on a NodeInfo taken straight from "
                                f"the snapshot map — route the mutation "
                                f"through `mutable_info(...)`"
                            ),
                        ))


def _dc605(rel, findings, funcs, src_lines, sync_ann, static_ann,
           used_static) -> None:
    n = len(src_lines)
    for ln, reason in sorted(sync_ann.items()):
        enc = _enclosing(funcs, ln)
        qual = enc.qualname if enc is not None else "<module>"
        if reason is None:
            findings.append(Finding(
                code="DC605", path=rel, line=ln, symbol=f"{qual}.L{ln}",
                message=(
                    "sync annotation without a reason: `# device: sync` "
                    "must carry `— <why this round-trip is in the budget>` "
                    "— a reasonless sanction is a silent waiver"
                ),
            ))
            continue
        here = src_lines[ln - 1]
        below = src_lines[ln] if ln < n else ""
        if not (_SYNC_LEXEME_RE.search(here)
                or _SYNC_LEXEME_RE.search(below)):
            findings.append(Finding(
                code="DC605", path=rel, line=ln, symbol=f"{qual}.L{ln}",
                message=(
                    "stale sync annotation: neither this line nor the next "
                    "contains a host-materialization call — the sanctioned "
                    "site moved or was removed; delete or move the "
                    "annotation so the sync budget stays honest"
                ),
            ))
    for ln in sorted(static_ann - used_static):
        enc = _enclosing(funcs, ln)
        qual = enc.qualname if enc is not None else "<module>"
        findings.append(Finding(
            code="DC605", path=rel, line=ln, symbol=f"{qual}.L{ln}",
            message=(
                "stale static annotation: `# device: static` sanctions no "
                "pad/width/compile-key site on this line or the next — "
                "delete or move it"
            ),
        ))


def run(
    root: str,
    paths: Optional[list[str]] = None,
    hot_modules: Optional[list[str]] = None,
) -> list[Finding]:
    """``hot_modules`` (default: tracecov's HOT_PATH_MODULES) bounds the
    DC602 sync-budget rule; it is intersected with the scanned set, so
    hot entries outside this pass's scope (store/, client/, …) are
    simply not DC602-checked here — tracecov's own fail-loud covers
    typos in the shared list."""
    files = iter_py_files(root, paths or DEFAULT_PATHS)
    hot = set(hot_modules if hot_modules is not None else HOT_PATH_MODULES)
    findings: list[Finding] = []
    for abs_path, rel in files:
        try:
            with open(abs_path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src)
        except SyntaxError as e:
            findings.append(Finding(
                code="DC600", path=rel, line=e.lineno or 1,
                symbol="<parse>",
                message=f"file does not parse: {e.msg}"))
            continue
        findings.extend(
            _analyze_file(rel, tree, src.splitlines(), hot))
    return findings


def sanctioned_sync_sites(
    root: str,
    paths: Optional[list[str]] = None,
) -> dict[str, dict[str, int]]:
    """Per-file, per-function count of VALID ``# device: sync`` sites —
    the static sync budget.  Lexical (annotation + materialization
    lexeme on the annotated or following line), matching DC605's
    validity rule, so the count equals what the pass sanctions.  The
    tier-1 runtime cross-check holds ``FrontierRun.stats['host_syncs']``
    to this bound."""
    out: dict[str, dict[str, int]] = {}
    for abs_path, rel in iter_py_files(root, paths or DEFAULT_PATHS):
        try:
            with open(abs_path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src)
        except SyntaxError:
            continue
        lines = src.splitlines()
        funcs = _collect_funcs(tree)
        sync_ann, _static = _scan_annotations(lines)
        per_fn: dict[str, int] = {}
        for ln, reason in sync_ann.items():
            if reason is None:
                continue
            here = lines[ln - 1]
            below = lines[ln] if ln < len(lines) else ""
            site = ln if _SYNC_LEXEME_RE.search(here) else (
                ln + 1 if _SYNC_LEXEME_RE.search(below) else None)
            if site is None:
                continue
            enc = _enclosing(funcs, site)
            qual = enc.qualname if enc is not None else "<module>"
            per_fn[qual] = per_fn.get(qual, 0) + 1
        if per_fn:
            out[rel] = per_fn
    return out
