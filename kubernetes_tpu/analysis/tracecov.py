"""Trace-coverage lint (TC5xx): span coverage is structural, not manual.

The flight recorder (ISSUE 7) is only as good as the sites that feed it:
a fault seam that fires outside any span leaves a blank where the
dump-on-fault story needs context, and a phase timer that never mirrors
to the trace layer makes the profile and the trace disagree about where
a wave's time went.  Until this pass, keeping those aligned was a
review-time convention; now it is a gate.

Rules
-----
- **TC500** — file in scope does not parse (same contract as RL300).
- **TC501** — a ``faults.hit(...)`` call site whose enclosing function is
  not *trace-covered*.  A function is trace-covered when it contains a
  trace marker itself (``.span(`` / ``.wave(`` / ``.complete(`` /
  ``.instant(`` call, or a ``NULL_SPAN`` reference — counted only in
  modules that import the tracing layer), or when every intra-module
  caller of its name is trace-covered (fixed point).  The caller rule is
  the trace twin of the races pass's caller-held-lock propagation: a
  helper extracted out of a span body (``bind_many`` →
  ``_bind_many_locked``) stays silent without a baseline entry.
- **TC502** — a phase timer ``X["<name>_s"] += t1 - t0`` in a phase-path
  file with no matching ``.complete("<name>", ...)`` in the same
  function: the stats profile and the trace would disagree about this
  phase.
- **TC503** — a wave-hot-path module with no trace marker at all: a new
  subsystem on the hot path must open at least one span before it ships.
- **TC504** — the inverse of TC503: a module that opens *wave-phase*
  spans (a ``.wave(`` call, or ``.complete(..., cat="phase")``) but is
  missing from ``HOT_PATH_MODULES``.  Wave phases feed the SLO burn-rate
  engine and the per-wave profile; a module emitting them from outside
  the declared hot set silently escapes the TC501/TC503 coverage gates,
  so the scope list must grow with the code — loudly.

Like every pass here the analysis is lexical and over-approximates
toward SILENCE: a marker anywhere in the function counts, whether or not
it lexically wraps the fault seam — the gate exists to catch modules and
functions with no trace story, not to prove dynamic nesting.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Finding, iter_py_files

DEFAULT_PATHS = ["kubernetes_tpu"]

#: modules on the wave hot path (store txn -> watch -> informer ->
#: scheduler -> backend): each must open at least one span (TC503)
HOT_PATH_MODULES = [
    "kubernetes_tpu/store/store.py",
    "kubernetes_tpu/store/wal.py",
    "kubernetes_tpu/client/informer.py",
    "kubernetes_tpu/client/remote.py",
    "kubernetes_tpu/scheduler/scheduler.py",
    "kubernetes_tpu/ops/backend.py",
    "kubernetes_tpu/ops/batch_kernel.py",
    "kubernetes_tpu/utils/overload.py",
    "kubernetes_tpu/parallel/mesh.py",
]

#: files whose ``*_s`` stats timers must mirror to the trace layer (TC502)
PHASE_FILES = [
    "kubernetes_tpu/ops/backend.py",
    "kubernetes_tpu/scheduler/scheduler.py",
]

_MARKER_ATTRS = {"span", "wave", "complete", "instant"}


def _imports_tracing(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and "tracing" in node.module:
                return True
            if any(a.name == "tracing" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("tracing" in a.name for a in node.names):
                return True
    return False


class _Func:
    __slots__ = ("node", "qualname", "name", "marked", "callers")

    def __init__(self, node: ast.FunctionDef, qualname: str):
        self.node = node
        self.qualname = qualname
        self.name = node.name
        self.marked = False
        self.callers: set[str] = set()  # caller function NAMES


def _collect_funcs(tree: ast.Module) -> list[_Func]:
    out: list[_Func] = []

    def visit(node, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                out.append(_Func(child, qual))
                visit(child, qual)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}" if prefix
                      else child.name)
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _enclosing(funcs: list[_Func], line: int) -> Optional[_Func]:
    best: Optional[_Func] = None
    for f in funcs:
        if f.node.lineno <= line <= (f.node.end_lineno or f.node.lineno):
            if best is None or f.node.lineno > best.node.lineno:
                best = f
    return best


def _is_marker(node: ast.AST) -> bool:
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return node.func.attr in _MARKER_ATTRS
    return isinstance(node, ast.Attribute) and node.attr == "NULL_SPAN"


def _is_fault_hit(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "hit"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "faults")


def _called_names(fn: ast.FunctionDef) -> set[str]:
    """Bare names this function calls: ``g(...)`` and ``self.g(...)``."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name):
            out.add(node.func.id)
        elif (isinstance(node.func, ast.Attribute)
              and isinstance(node.func.value, ast.Name)
              and node.func.value.id == "self"):
            out.add(node.func.attr)
    return out


def _covered_names(funcs: list[_Func]) -> set[str]:
    """Fixed point of marker coverage over the intra-module call graph:
    own marker, or every known caller covered.  Name-level (not
    instance-level) on both sides — over-approximates toward silence."""
    for f in funcs:
        for name in _called_names(f.node):
            for g in funcs:
                if g.name == name:
                    g.callers.add(f.name)
    covered = {f.name for f in funcs if f.marked}
    changed = True
    while changed:
        changed = False
        for f in funcs:
            if f.name in covered or not f.callers:
                continue
            if f.callers <= covered:
                covered.add(f.name)
                changed = True
    return covered


def _fault_label(call: ast.Call) -> str:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return "hit"


def _phase_timer_key(node: ast.AugAssign) -> Optional[str]:
    """``X["<k>_s"] += a - b`` -> ``<k>``; None for anything else."""
    if not isinstance(node.op, ast.Add):
        return None
    if not isinstance(node.target, ast.Subscript):
        return None
    sl = node.target.slice
    if not (isinstance(sl, ast.Constant) and isinstance(sl.value, str)
            and sl.value.endswith("_s")):
        return None
    if not (isinstance(node.value, ast.BinOp)
            and isinstance(node.value.op, ast.Sub)):
        return None
    return sl.value[:-2]


def _wave_phase_marker_line(tree: ast.Module) -> Optional[int]:
    """First line opening a *wave-phase* span — a ``.wave(`` call or a
    ``.complete(..., cat="phase")`` call — or None.  ``cat="trace"`` and
    other categories are background instrumentation, not wave phases."""
    best: Optional[int] = None
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        hit = node.func.attr == "wave"
        if not hit and node.func.attr == "complete":
            hit = any(kw.arg == "cat"
                      and isinstance(kw.value, ast.Constant)
                      and kw.value.value == "phase"
                      for kw in node.keywords)
        if hit and (best is None or node.lineno < best):
            best = node.lineno
    return best


def _completes_in(fn: ast.FunctionDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "complete"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.add(node.args[0].value)
    return out


def run(
    root: str,
    paths: Optional[list[str]] = None,
    hot_modules: Optional[list[str]] = None,
    phase_files: Optional[list[str]] = None,
) -> list[Finding]:
    files = iter_py_files(root, paths or DEFAULT_PATHS)
    hot = set(hot_modules if hot_modules is not None else HOT_PATH_MODULES)
    phase = set(phase_files if phase_files is not None else PHASE_FILES)
    findings: list[Finding] = []

    seen_rel: set[str] = set()
    for abs_path, rel in files:
        seen_rel.add(rel)
        try:
            with open(abs_path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except SyntaxError as e:
            findings.append(Finding(
                code="TC500", path=rel, line=e.lineno or 1,
                symbol="<parse>",
                message=f"file does not parse: {e.msg}"))
            continue

        traced_module = _imports_tracing(tree)
        funcs = _collect_funcs(tree)
        marker_lines: list[int] = []
        if traced_module:
            for node in ast.walk(tree):
                if _is_marker(node):
                    marker_lines.append(node.lineno)
        for f in funcs:
            a, b = f.node.lineno, f.node.end_lineno or f.node.lineno
            if any(a <= ln <= b for ln in marker_lines):
                f.marked = True
        covered = _covered_names(funcs)

        # TC501: fault seams outside any trace-covered function
        for node in ast.walk(tree):
            if not _is_fault_hit(node):
                continue
            enc = _enclosing(funcs, node.lineno)
            if enc is not None and enc.name in covered:
                continue
            where = enc.qualname if enc is not None else "<module>"
            label = _fault_label(node)
            findings.append(Finding(
                code="TC501", path=rel, line=node.lineno,
                symbol=f"{where}.{label}",
                message=(
                    f"fault seam `faults.hit({label!r}, ...)` executes "
                    f"outside any span: `{where}` opens no span/marker and "
                    f"neither do all of its callers — a dump-on-fault here "
                    f"has no trace context"
                ),
            ))

        # TC502: phase timers not mirrored to the trace layer
        if rel in phase:
            for f in funcs:
                completes = None
                for node in ast.walk(f.node):
                    if not isinstance(node, ast.AugAssign):
                        continue
                    key = _phase_timer_key(node)
                    if key is None:
                        continue
                    # only the innermost function owns the timer
                    if _enclosing(funcs, node.lineno) is not f:
                        continue
                    if completes is None:
                        completes = _completes_in(f.node)
                    if key in completes:
                        continue
                    findings.append(Finding(
                        code="TC502", path=rel, line=node.lineno,
                        symbol=f"{f.qualname}.{key}_s",
                        message=(
                            f"phase timer `{key}_s` accumulated in "
                            f"`{f.qualname}` with no matching "
                            f"`.complete({key!r}, ...)` — the stats "
                            f"profile and the trace disagree about this "
                            f"phase"
                        ),
                    ))

        # TC503: hot-path module with no trace story at all
        if rel in hot and not marker_lines:
            findings.append(Finding(
                code="TC503", path=rel, line=1, symbol="<module>",
                message=(
                    "wave-hot-path module opens no span (no .span/.wave/"
                    ".complete/.instant call and no NULL_SPAN use" +
                    ("" if traced_module
                     else "; the tracing layer is not even imported") +
                    ") — waves crossing this module are invisible to the "
                    "flight recorder"
                ),
            ))

        # TC504: wave-phase spans opened outside the declared hot set
        if rel not in hot:
            ln = _wave_phase_marker_line(tree)
            if ln is not None:
                findings.append(Finding(
                    code="TC504", path=rel, line=ln, symbol="<module>",
                    message=(
                        "module opens wave-phase spans (`.wave(` / "
                        "`.complete(..., cat=\"phase\")`) but is not "
                        "listed in HOT_PATH_MODULES — it escapes the "
                        "TC501/TC503 coverage gates and its phases feed "
                        "the SLO engine unaudited; add it to the hot "
                        "scope (or the scope override)"
                    ),
                ))

    # a hot/phase scope entry that matches no scanned file is a config
    # error of THIS pass: fail loud, mirroring iter_py_files's contract
    for rel in sorted((hot | phase) - seen_rel):
        findings.append(Finding(
            code="TC500", path=rel, line=1, symbol="<scope>",
            message=(
                "trace-coverage scope names a file outside the scanned "
                "set — fix HOT_PATH_MODULES/PHASE_FILES (or the scope "
                "override) rather than silently checking nothing"
            ),
        ))
    return findings
