"""Pass 1 — trace-safety over the kernel packages (TS1xx).

Host Python leaking into traced JAX/Pallas code fails in one of two ways:
loudly at trace time (ConcretizationError) on the paths tests exercise, or
*silently* on paths they don't — a ``float()`` on a traced value bakes one
trace-time constant into the compiled program forever.  This pass finds
both shapes before they compile.

What counts as *traced* (the call-graph part):

- a function decorated ``@jax.jit`` / ``@jit`` / ``partial(jax.jit, …)``;
- a function passed by name into a tracing consumer
  (``lax.scan/fori_loop/while_loop/cond/switch``, ``pl.pallas_call``,
  ``jax.vmap/pmap/grad/remat/checkpoint/shard_map``) — including when
  the reference rides a ``functools.partial(fn, …)`` wrapper (direct
  argument or a module/class-level ``name = partial(fn, …)`` alias) or a
  bound-method reference (``self._step`` → the method def);
- transitively: any function called by simple name OR as a
  ``self.method(...)`` / ``cls.method(...)`` call from a traced function,
  any function a traced body wraps in ``functools.partial``, and any
  function *defined inside* a traced function (factory bodies like
  ``make_step`` run under trace).

What counts as *kernel-derived* (the taint part): the traced function's
own parameters plus anything dataflow-derived from them or from a
``jnp.``/``jax.``/``pl.``/``pltpu.`` expression.  Free (closure)
variables are NOT tainted — they are the standard way static
configuration reaches a traced body — and neither are parameters
annotated ``bool`` or defaulted to a bool/None literal, the project's
static-flag idiom (``use_terms: bool``, ``most: bool``).

Findings:

- TS101 host escape: ``float()/int()/bool()`` on a tainted value,
  ``.item()/.tolist()`` on a tainted value, or any ``np.``/``numpy.``
  call inside a traced body.
- TS102 Python branch on a traced value: ``if``/``while`` whose test
  reads a tainted name.  Pure ``is``/``is not`` tests are exempt
  (identity never concretizes a tracer).
- TS103 nondeterministic set iteration feeding tensor builders: a
  ``for`` (or comprehension) over a set display/comprehension/``set()``
  result, not wrapped in ``sorted()``, in a function that also builds
  tensors (``np/jnp .array/asarray/zeros/full/stack/…``).  Scanned in
  ALL functions, not just traced ones — the host-side tensorizer is
  where iteration order becomes device-visible data.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Finding, iter_py_files

DEFAULT_PATHS = [
    "kubernetes_tpu/ops",
    "kubernetes_tpu/models",
    "kubernetes_tpu/parallel",
]

TRACING_CONSUMERS = {
    "scan",
    "fori_loop",
    "while_loop",
    "cond",
    "switch",
    "pallas_call",
    "vmap",
    "pmap",
    "grad",
    "value_and_grad",
    "remat",
    "checkpoint",
    "shard_map",
    "associative_scan",
}
JIT_NAMES = {"jit"}
DEVICE_MODULES = {"jnp", "jax", "lax", "pl", "pltpu"}
HOST_CAST_CALLS = {"float", "int", "bool", "complex"}
HOST_ATTR_CALLS = {"item", "tolist", "numpy"}
NP_MODULES = {"np", "numpy", "onp"}
TENSOR_BUILDER_ATTRS = {
    "array",
    "asarray",
    "stack",
    "concatenate",
    "zeros",
    "ones",
    "full",
    "empty",
    "frombuffer",
    "fromiter",
}

FuncNode = "ast.FunctionDef | ast.AsyncFunctionDef"


def _func_defs(tree: ast.AST) -> list[tuple[ast.AST, tuple[str, ...]]]:
    """All function defs with their dotted scope path (classes included)."""
    out: list[tuple[ast.AST, tuple[str, ...]]] = []

    def walk(node: ast.AST, scope: tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((child, scope + (child.name,)))
                walk(child, scope + (child.name,))
            elif isinstance(child, ast.ClassDef):
                walk(child, scope + (child.name,))
            else:
                walk(child, scope)

    walk(tree, ())
    return out


def _is_jit_decorator(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Name) and dec.id in JIT_NAMES:
        return True
    if isinstance(dec, ast.Attribute) and dec.attr in JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        # @jax.jit(static_argnums=…) and @partial(jax.jit, …)
        if _is_jit_decorator(dec.func):
            return True
        fn = dec.func
        if (isinstance(fn, ast.Name) and fn.id == "partial") or (
            isinstance(fn, ast.Attribute) and fn.attr == "partial"
        ):
            return any(_is_jit_decorator(a) for a in dec.args)
    return False


def _call_target_attr(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _is_static_flag_param(arg: ast.arg, default: Optional[ast.expr]) -> bool:
    """bool-annotated or bool/None-defaulted parameters are the static-flag
    idiom — excluded from taint."""
    ann = arg.annotation
    if isinstance(ann, ast.Name) and ann.id == "bool":
        return True
    if isinstance(ann, ast.Constant) and ann.value == "bool":
        return True
    if isinstance(default, ast.Constant) and (
        default.value is None or isinstance(default.value, bool)
    ):
        return True
    return False


def _is_partial_call(call: ast.Call) -> bool:
    fn = call.func
    return (isinstance(fn, ast.Name) and fn.id == "partial") or (
        isinstance(fn, ast.Attribute) and fn.attr == "partial"
    )


class _ModuleTraceIndex:
    """Which functions in one module execute under trace."""

    def __init__(self, tree: ast.AST):
        self.defs = _func_defs(tree)
        self.by_node: dict[ast.AST, tuple[str, ...]] = {
            node: path for node, path in self.defs
        }
        self.by_name: dict[str, list[ast.AST]] = {}
        for node, path in self.defs:
            self.by_name.setdefault(path[-1], []).append(node)
        # name = partial(fn, ...) aliases (module/class/function level):
        # a consumer receiving the alias name traces the wrapped fn
        self.partial_aliases: dict[str, list[str]] = {}
        for stmt in ast.walk(tree):
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)) and stmt.value is not None:
                refs = self._partial_refs(stmt.value)
                if refs:
                    targets = (stmt.targets if isinstance(stmt, ast.Assign)
                               else [stmt.target])
                    for t in targets:
                        for name in _assigned_names(t):
                            self.partial_aliases.setdefault(name, []).extend(refs)
        self.traced: set[ast.AST] = set()
        self._seed_roots(tree)
        self._closure()

    def _partial_refs(self, expr: ast.expr) -> list[str]:
        """Function names a ``partial(...)`` expression wraps (first
        positional arg, by bare name or attribute tail)."""
        if not (isinstance(expr, ast.Call) and _is_partial_call(expr) and expr.args):
            return []
        head = expr.args[0]
        if isinstance(head, ast.Name):
            return [head.id]
        if isinstance(head, ast.Attribute):
            return [head.attr]
        return []

    def _callable_refs(self, arg: ast.expr) -> list[str]:
        """Possible function-def names one consumer argument references:
        a bare name, a bound-method reference (``self._step`` →
        ``_step``), a ``partial(fn, …)`` wrapper, or a name aliasing a
        partial (interprocedural taint, ROADMAP open item)."""
        if isinstance(arg, ast.Name):
            return [arg.id] + self.partial_aliases.get(arg.id, [])
        if isinstance(arg, ast.Attribute):
            return [arg.attr]
        refs = self._partial_refs(arg)
        out = list(refs)
        for r in refs:
            out.extend(self.partial_aliases.get(r, []))
        return out

    def _seed_roots(self, tree: ast.AST) -> None:
        for node, _path in self.defs:
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                self.traced.add(node)
        for call in (n for n in ast.walk(tree) if isinstance(n, ast.Call)):
            if _call_target_attr(call) in TRACING_CONSUMERS:
                for arg in list(call.args) + [kw.value for kw in call.keywords]:
                    for name in self._callable_refs(arg):
                        for fn in self.by_name.get(name, ()):
                            self.traced.add(fn)

    def _closure(self) -> None:
        changed = True
        while changed:
            changed = False
            for node in list(self.traced):
                # nested defs run at trace time
                for child, _ in self.defs:
                    if child not in self.traced and self._encloses(node, child):
                        self.traced.add(child)
                        changed = True
                for call in (n for n in ast.walk(node) if isinstance(n, ast.Call)):
                    # simple-name calls out of a traced body
                    names: list[str] = []
                    if isinstance(call.func, ast.Name):
                        names.append(call.func.id)
                    # bound-method calls: self.helper(...) / cls.helper(...)
                    # run under the same trace (only the self/cls receiver
                    # is followed — other attribute calls are library code)
                    elif (isinstance(call.func, ast.Attribute)
                          and isinstance(call.func.value, ast.Name)
                          and call.func.value.id in ("self", "cls")):
                        names.append(call.func.attr)
                    # a traced body wrapping a helper in partial(...) will
                    # call it under trace wherever the wrapper flows
                    if _is_partial_call(call):
                        names.extend(self._partial_refs(call))
                    for name in names:
                        for fn in self.by_name.get(name, ()):
                            if fn not in self.traced:
                                self.traced.add(fn)
                                changed = True

    def _encloses(self, outer: ast.AST, inner: ast.AST) -> bool:
        return inner is not outer and any(
            n is inner
            for n in ast.walk(outer)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        )


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _has_device_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, (ast.Attribute, ast.Call)):
            root = n
            while isinstance(root, ast.Call):
                root = root.func
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in DEVICE_MODULES:
                return True
    return False


def _tainted_params(fn) -> set[str]:
    args = fn.args
    pos = list(args.posonlyargs) + list(args.args)
    defaults = list(args.defaults)
    # right-align defaults with positional args
    pad: list[Optional[ast.expr]] = [None] * (len(pos) - len(defaults)) + defaults
    tainted: set[str] = set()
    for arg, default in zip(pos, pad):
        if arg.arg == "self":
            continue
        if not _is_static_flag_param(arg, default):
            tainted.add(arg.arg)
    for arg, default in zip(args.kwonlyargs, args.kw_defaults):
        if not _is_static_flag_param(arg, default):
            tainted.add(arg.arg)
    if args.vararg:
        tainted.add(args.vararg.arg)
    if args.kwarg:
        tainted.add(args.kwarg.arg)
    return tainted


def _assigned_names(target: ast.expr) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for el in target.elts:
            out.extend(_assigned_names(el))
        return out
    if isinstance(target, ast.Starred):
        return _assigned_names(target.value)
    return []


class _TraceBodyChecker(ast.NodeVisitor):
    """TS101/TS102 inside one traced function (nested defs are analyzed in
    their own right and skipped here)."""

    def __init__(self, fn, qual: str, rel: str, findings: list[Finding]):
        self.fn = fn
        self.qual = qual
        self.rel = rel
        self.findings = findings
        self.tainted = _tainted_params(fn)
        # dataflow fixpoint: two forward passes over the body cover the
        # loop-carried case (a name tainted later in a loop body)
        for _ in range(2):
            for stmt in ast.walk(fn):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    value = stmt.value
                    if value is None:
                        continue
                    targets = (
                        stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                    )
                    if self._expr_tainted(value):
                        for t in targets:
                            self.tainted.update(_assigned_names(t))
                elif isinstance(stmt, ast.For):
                    if self._expr_tainted(stmt.iter):
                        self.tainted.update(_assigned_names(stmt.target))

    def _expr_tainted(self, expr: ast.expr) -> bool:
        return bool(_names_in(expr) & self.tainted) or _has_device_call(expr)

    def _emit(self, code: str, node: ast.AST, symbol_tail: str, msg: str) -> None:
        self.findings.append(
            Finding(
                code=code,
                path=self.rel,
                line=node.lineno,
                symbol=f"{self.qual}.{symbol_tail}",
                message=msg,
            )
        )

    # nested functions get their own checker
    def visit_FunctionDef(self, node) -> None:
        if node is not self.fn:
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in HOST_CAST_CALLS:
            if any(self._expr_tainted(a) for a in node.args):
                self._emit(
                    "TS101",
                    node,
                    fn.id,
                    f"host escape: `{fn.id}()` on a traced value concretizes at "
                    f"trace time (bakes a constant into the compiled program)",
                )
        elif isinstance(fn, ast.Attribute):
            if fn.attr in HOST_ATTR_CALLS and self._expr_tainted(fn.value):
                self._emit(
                    "TS101",
                    node,
                    fn.attr,
                    f"host escape: `.{fn.attr}()` on a traced value forces a "
                    f"device→host sync inside a traced body",
                )
            else:
                root = fn
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in NP_MODULES:
                    self._emit(
                        "TS101",
                        node,
                        f"{root.id}.{fn.attr}",
                        f"host escape: `{root.id}.{fn.attr}()` call inside a traced "
                        f"body runs on host at trace time, not on the device",
                    )
        self.generic_visit(node)

    def _check_branch(self, node, kind: str) -> None:
        test = node.test
        if _is_identity_only(test):
            return
        hit = _names_in(test) & self.tainted
        if hit:
            self._emit(
                "TS102",
                node,
                f"{kind}.{'.'.join(sorted(hit))}",
                f"Python `{kind}` on traced value(s) {sorted(hit)}: use "
                f"`jnp.where`/`lax.cond` (host branching concretizes the tracer)",
            )

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, "while")
        self.generic_visit(node)


def _is_identity_only(test: ast.expr) -> bool:
    """`x is None` / `x is not None` never concretizes a tracer."""
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    )


def _set_typed_names(fn) -> dict[str, int]:
    """Local names assigned a set display/comprehension/`set()` call."""
    out: dict[str, int] = {}
    for stmt in ast.walk(fn):
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            if stmt.value is None:
                continue
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            if _is_set_expr(stmt.value):
                for t in targets:
                    for name in _assigned_names(t):
                        out[name] = stmt.lineno
            else:
                for t in targets:
                    for name in _assigned_names(t):
                        out.pop(name, None)
    return out


def _is_set_expr(expr: ast.expr) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return True
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
        return _is_set_expr(expr.left) or _is_set_expr(expr.right)
    return False


def _check_set_iteration(fn, qual: str, rel: str, findings: list[Finding]) -> None:
    has_builder = any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr in TENSOR_BUILDER_ATTRS
        and isinstance(n.func.value, ast.Name)
        and n.func.value.id in (NP_MODULES | {"jnp"})
        for n in ast.walk(fn)
    )
    if not has_builder:
        return
    set_names = _set_typed_names(fn)

    def iter_expr_is_set(it: ast.expr) -> bool:
        if _is_set_expr(it):
            return True
        return isinstance(it, ast.Name) and it.id in set_names

    loops: list[tuple[ast.AST, ast.expr]] = []
    for n in ast.walk(fn):
        if isinstance(n, ast.For):
            loops.append((n, n.iter))
        elif isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            for gen in n.generators:
                loops.append((n, gen.iter))
    for node, it in loops:
        if iter_expr_is_set(it):
            findings.append(
                Finding(
                    code="TS103",
                    path=rel,
                    line=node.lineno,
                    symbol=f"{qual}.set-iter",
                    message=(
                        "iteration over a set in a tensor-building function: set "
                        "order is nondeterministic across processes — sort "
                        "(`sorted(...)`) before it can reach array contents"
                    ),
                )
            )


def run(root: str, paths: Optional[list[str]] = None) -> list[Finding]:
    findings: list[Finding] = []
    for abs_path, rel in iter_py_files(root, paths or DEFAULT_PATHS):
        with open(abs_path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            findings.append(
                Finding(
                    code="TS100",
                    path=rel,
                    line=e.lineno or 1,
                    symbol="syntax",
                    message=f"unparseable file: {e.msg}",
                )
            )
            continue
        index = _ModuleTraceIndex(tree)
        for fn, path in index.defs:
            qual = ".".join(path)
            if fn in index.traced:
                checker = _TraceBodyChecker(fn, qual, rel, findings)
                for stmt in fn.body:
                    checker.visit(stmt)
            _check_set_iteration(fn, qual, rel, findings)
    # one symbol can only anchor one finding per line (dedupe repeated walks)
    seen: set[tuple] = set()
    out: list[Finding] = []
    for f in findings:
        k = (f.code, f.path, f.line, f.symbol)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
