"""ktpu-analyze CLI.

    python -m kubernetes_tpu.analysis [--json] [--pass NAME]...
                                      [--baseline PATH | --no-baseline]
                                      [--prune-baseline] [--profile]
                                      [--changed[=REF]]
                                      [--root DIR] [--list-passes]

Exit codes: 0 = clean (all findings baselined), 1 = unbaselined findings,
2 = usage/baseline error.  Nonzero-on-findings is the commit-gate
contract: `python -m kubernetes_tpu.analysis && git commit …`.
``--prune-baseline`` rewrites the baseline file with stale entries
removed (reasons on surviving entries preserved); exit semantics are
unchanged — findings still fail the run after the prune.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys

from .core import (
    _CODE_PREFIX_PASS,
    PASS_NAMES,
    BaselineError,
    default_baseline_path,
    load_baseline,
    prune_baseline,
    repo_root,
    run_analysis,
)


def _changed_files(root: str, ref: str) -> set[str]:
    """Repo-relative paths changed vs ``ref`` plus untracked files.

    Raises ValueError on a bad ref (surfaced as exit 2): a typo'd ref
    must not silently report zero files as 'nothing changed'."""
    diff = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        cwd=root, capture_output=True, text=True,
    )
    if diff.returncode != 0:
        raise ValueError(
            f"--changed: git diff against {ref!r} failed: "
            f"{diff.stderr.strip() or 'unknown git error'}"
        )
    out = {line.strip() for line in diff.stdout.splitlines() if line.strip()}
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=root, capture_output=True, text=True,
    )
    if untracked.returncode == 0:
        out.update(line.strip() for line in untracked.stdout.splitlines()
                   if line.strip())
    return out

PASS_DESCRIPTIONS = {
    "trace": "trace-safety over ops/ (TS1xx: host escapes, Python branches on traced values, set-order nondeterminism)",
    "parity": "oracle↔kernel parity coverage (PC2xx: unmapped predicates/priorities, stale markers)",
    "races": "controller/kubelet race lint (RL3xx: unlocked cross-thread writes, lock-order cycles)",
    "metrics": "metrics-name lint (MN4xx: snake_case names, counters end _total, histograms carry a unit, no duplicate registrations, SLO specs resolve to registered metrics)",
    "tracecov": "trace-coverage lint (TC5xx: fault seams outside spans, unmirrored phase timers, span-free hot-path modules, wave-phase spans outside the hot scope)",
    "device": "device-contract lint (DC6xx: use-after-donate, unsanctioned host syncs on the wave hot path, shape-bearing values at jit boundaries, snapshot writes bypassing clone-on-write)",
    "concurrency": "concurrency-hazard & resource-lifecycle lint (CH7xx: blocking calls under held locks, swallowed exceptions, unjoined threads / unclosed handles, callbacks invoked under locks, unbounded growth on daemon paths)",
}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="Project-native static analysis: trace-safety, parity coverage, race lint.",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable report on stdout")
    parser.add_argument(
        "--pass",
        dest="passes",
        action="append",
        choices=PASS_NAMES,
        help="run only the named pass (repeatable; default: all)",
    )
    baseline_group = parser.add_mutually_exclusive_group()
    baseline_group.add_argument(
        "--baseline", default=None, help="baseline suppression file (JSON)"
    )
    baseline_group.add_argument(
        "--no-baseline", action="store_true", help="report every finding, suppressing nothing"
    )
    parser.add_argument("--root", default=None, help="repo root (default: autodetected)")
    parser.add_argument(
        "--strict-baseline",
        action="store_true",
        help="also fail (exit 1) on stale baseline entries",
    )
    parser.add_argument(
        "--prune-baseline",
        action="store_true",
        help="rewrite the baseline file with stale entries removed "
             "(surviving entries keep their reasons and order)",
    )
    parser.add_argument(
        "--changed",
        nargs="?",
        const="HEAD",
        default=None,
        metavar="REF",
        help="report findings only for files changed vs REF (default: HEAD) "
             "plus untracked files — the full scope is still scanned, so "
             "cross-file summaries and stale-baseline detection stay exact; "
             "only the REPORT is diff-scoped",
    )
    parser.add_argument(
        "--profile",
        action="store_true",
        help="print per-pass wall-time to stderr",
    )
    parser.add_argument("--list-passes", action="store_true", help="list passes and exit")
    args = parser.parse_args(argv)

    if args.list_passes:
        for name in PASS_NAMES:
            print(f"{name:8s} {PASS_DESCRIPTIONS[name]}")
        return 0

    baseline = None
    baseline_path = None
    if args.prune_baseline and args.no_baseline:
        print("--prune-baseline needs a baseline file (conflicts with "
              "--no-baseline)", file=sys.stderr)
        return 2
    if not args.no_baseline:
        baseline_path = args.baseline or default_baseline_path()
        try:
            baseline = load_baseline(baseline_path)
        except FileNotFoundError:
            print(f"baseline file not found: {baseline_path}", file=sys.stderr)
            return 2
        except BaselineError as e:
            print(str(e), file=sys.stderr)
            return 2

    try:
        report = run_analysis(
            root=args.root or repo_root(), passes=args.passes, baseline=baseline
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.prune_baseline and report.stale_suppressions:
        removed = prune_baseline(baseline_path, report.stale_suppressions)
        for key in removed:
            code = key.split(":", 1)[0]
            pass_name = _CODE_PREFIX_PASS.get(code[:2], "unknown")
            print(f"pruned stale baseline entry [{pass_name} {code}]: {key}",
                  file=sys.stderr)
        report.stale_suppressions = []

    if args.changed is not None:
        try:
            changed = _changed_files(args.root or repo_root(), args.changed)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        report.findings = [f for f in report.findings if f.path in changed]
        report.suppressed = [f for f in report.suppressed if f.path in changed]

    if args.json:
        # sort_keys: CI diffs two runs' output textually — field order
        # must never depend on dict construction order
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        print(report.format_text())
    if args.profile:
        for name in report.passes_run:
            print(f"profile: {name:8s} {report.timings.get(name, 0.0) * 1000.0:8.1f} ms",
                  file=sys.stderr)
    if report.findings:
        return 1
    if args.strict_baseline and report.stale_suppressions:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
