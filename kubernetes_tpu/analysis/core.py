"""Analyzer spine: findings, baseline suppressions, reporters, pass driver.

A :class:`Finding` is keyed by ``code:path:symbol`` — deliberately NOT by
line number, so a baseline entry survives unrelated edits above the
finding.  ``symbol`` is the dotted enclosing-scope path plus the offending
name (e.g. ``make_step.step.gid`` or ``Controller._worker_loop.counter``),
which moves only when the code it names moves.

The baseline file is a checked-in JSON document; every suppression MUST
carry a non-empty ``reason`` (enforced at load time) so nothing is ever
waved through silently.  Stale entries (keys matching no current finding)
are reported so the baseline can only shrink, never rot.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable, Optional

PASS_NAMES = ("trace", "parity", "races", "metrics", "tracecov", "device",
              "concurrency")


def repo_root() -> str:
    """The directory containing the ``kubernetes_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


@dataclass(frozen=True)
class Finding:
    code: str  # e.g. "TS101"
    path: str  # repo-relative posix path
    line: int  # 1-based, for humans; not part of the key
    symbol: str  # stable anchor (scope path + name)
    message: str

    @property
    def key(self) -> str:
        return f"{self.code}:{self.path}:{self.symbol}"

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "key": self.key,
        }

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.code} [{self.symbol}] {self.message}"


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, missing/empty reason)."""


def load_baseline(path: str) -> dict[str, str]:
    """key -> justification.  Every entry must justify itself."""
    with open(path, "r", encoding="utf-8") as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise BaselineError(f"{path}: invalid JSON: {e}") from e
    entries = doc.get("suppressions")
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: expected a top-level 'suppressions' list")
    out: dict[str, str] = {}
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict) or "key" not in entry:
            raise BaselineError(f"{path}: suppression #{i} has no 'key'")
        reason = entry.get("reason")
        if not isinstance(reason, str) or not reason.strip():
            raise BaselineError(
                f"{path}: suppression {entry['key']!r} has no justification "
                f"('reason' must be a non-empty string)"
            )
        if entry["key"] in out:
            raise BaselineError(f"{path}: duplicate suppression {entry['key']!r}")
        out[entry["key"]] = reason.strip()
    return out


def prune_baseline(path: str, stale_keys: list[str]) -> list[str]:
    """Rewrite the baseline file with the given stale entries removed.

    Surviving entries keep their order, reasons, and any extra fields;
    top-level keys other than ``suppressions`` (the ``_comment`` header)
    are preserved verbatim.  Returns the keys actually removed.  The file
    is validated through :func:`load_baseline` first so a malformed
    baseline is an error, never a silent truncation."""
    load_baseline(path)  # raises BaselineError on anything malformed
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    stale = set(stale_keys)
    kept, removed = [], []
    for entry in doc["suppressions"]:
        if entry["key"] in stale:
            removed.append(entry["key"])
        else:
            kept.append(entry)
    if removed:
        doc["suppressions"] = kept
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    return removed


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    stale_suppressions: list[str] = field(default_factory=list)
    passes_run: list[str] = field(default_factory=list)
    timings: dict[str, float] = field(default_factory=dict)  # pass -> seconds

    def counts(self) -> dict[str, dict[str, int]]:
        """Per-pass finding/suppression totals, keyed by pass name, every
        requested pass present (zeros included) — the stable shape CI
        diffs between runs."""
        out = {p: {"findings": 0, "suppressed": 0} for p in self.passes_run}
        for bucket, fs in (("findings", self.findings),
                           ("suppressed", self.suppressed)):
            for f in fs:
                p = _CODE_PREFIX_PASS.get(f.code[:2])
                if p in out:
                    out[p][bucket] += 1
        return out

    def to_dict(self) -> dict:
        return {
            "passes": self.passes_run,
            "counts": self.counts(),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "stale_suppressions": self.stale_suppressions,
            "timings_ms": {p: round(t * 1000.0, 3)
                           for p, t in self.timings.items()},
        }

    def format_text(self) -> str:
        lines: list[str] = []
        by_file: dict[str, list[Finding]] = {}
        for f in self.findings:
            by_file.setdefault(f.path, []).append(f)
        for path in sorted(by_file):
            lines.append(path)
            for f in sorted(by_file[path], key=lambda x: (x.line, x.code)):
                lines.append(f"  {f.line}: {f.code} [{f.symbol}] {f.message}")
        lines.append(
            f"{len(self.findings)} finding(s), {len(self.suppressed)} baselined, "
            f"passes: {', '.join(self.passes_run)}"
        )
        if self.stale_suppressions:
            lines.append(
                f"warning: {len(self.stale_suppressions)} stale baseline entr"
                f"{'y' if len(self.stale_suppressions) == 1 else 'ies'} "
                f"(matched nothing — prune them):"
            )
            for key in self.stale_suppressions:
                lines.append(f"  {key}")
        return "\n".join(lines)


# finding-code prefix -> the pass that can produce it (stale-entry
# detection must not call a races suppression "stale" in a parity-only run)
_CODE_PREFIX_PASS = {"TS": "trace", "PC": "parity", "RL": "races",
                     "MN": "metrics", "TC": "tracecov", "DC": "device",
                     "CH": "concurrency"}


def _split_baseline(
    findings: list[Finding], baseline: dict[str, str], passes: list[str]
) -> tuple[list[Finding], list[Finding], list[str]]:
    live: list[Finding] = []
    suppressed: list[Finding] = []
    used: set[str] = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            used.add(f.key)
        else:
            live.append(f)
    stale = sorted(
        key
        for key in set(baseline) - used
        if _CODE_PREFIX_PASS.get(key[:2], passes[0] if passes else "") in passes
    )
    return live, suppressed, stale


def run_analysis(
    root: Optional[str] = None,
    passes: Optional[list[str]] = None,
    baseline: Optional[dict[str, str]] = None,
    scopes: Optional[dict[str, dict]] = None,
) -> Report:
    """Run the requested passes over the tree at ``root``.

    ``scopes`` overrides per-pass file scopes (used by the fixture tests to
    aim a pass at seeded-violation files): ``{"trace": {"paths": [...]},
    "parity": {"oracle_paths": [...], "kernel_paths": [...]},
    "races": {"paths": [...]}}``.
    """
    import time

    from . import (concurrency_hazards, device_contracts, metrics_lint,
                   parity, races, trace_safety, tracecov)

    root = root or repo_root()
    passes = list(passes) if passes else list(PASS_NAMES)
    scopes = scopes or {}
    unknown = [p for p in passes if p not in PASS_NAMES]
    if unknown:
        raise ValueError(f"unknown pass(es): {unknown}; valid: {list(PASS_NAMES)}")

    runners: dict[str, Callable[[], list[Finding]]] = {
        "trace": lambda: trace_safety.run(root, **scopes.get("trace", {})),
        "parity": lambda: parity.run(root, **scopes.get("parity", {})),
        "races": lambda: races.run(root, **scopes.get("races", {})),
        "metrics": lambda: metrics_lint.run(root, **scopes.get("metrics", {})),
        "tracecov": lambda: tracecov.run(root, **scopes.get("tracecov", {})),
        "device": lambda: device_contracts.run(root, **scopes.get("device", {})),
        "concurrency": lambda: concurrency_hazards.run(
            root, **scopes.get("concurrency", {})),
    }
    findings: list[Finding] = []
    timings: dict[str, float] = {}
    for name in passes:
        t0 = time.perf_counter()
        findings.extend(runners[name]())
        timings[name] = time.perf_counter() - t0
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))

    report = Report(passes_run=passes, timings=timings)
    if baseline:
        report.findings, report.suppressed, report.stale_suppressions = _split_baseline(
            findings, baseline, passes
        )
    else:
        report.findings = findings
    return report


def iter_py_files(root: str, rel_paths: list[str]) -> list[tuple[str, str]]:
    """Expand repo-relative files/directories into (abs_path, rel_path)
    pairs, sorted for deterministic finding order.

    A scope path that matches nothing is a hard error: a typo'd or renamed
    entry must not silently shrink the gate's coverage to zero files."""
    out: list[tuple[str, str]] = []
    for rel in rel_paths:
        abs_p = os.path.join(root, rel)
        if not os.path.exists(abs_p):
            raise ValueError(
                f"analysis scope path does not exist: {rel!r} (under {root}) — "
                f"fix the scope list rather than scanning nothing"
            )
        if os.path.isdir(abs_p):
            for dirpath, dirnames, filenames in os.walk(abs_p):
                dirnames.sort()
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        out.append((full, os.path.relpath(full, root).replace(os.sep, "/")))
        elif os.path.isfile(abs_p):
            out.append((abs_p, rel.replace(os.sep, "/")))
    return out
