"""Shared daemon runtime: signals, leader election loop, serve-forever.

The reference ships eight runnable binaries (``cmd/*``,
``plugin/cmd/kube-scheduler``); the entry points here are their
process-model equivalent, started as::

    python -m kubernetes_tpu.apiserver   --port 6443 --token-file tokens
    python -m kubernetes_tpu.scheduler   --apiserver http://host:6443 --leader-elect
    python -m kubernetes_tpu.controllers --apiserver http://host:6443 --leader-elect
    python -m kubernetes_tpu.kubelet     --apiserver http://host:6443 --name n1 --proxy

Each wires threaded informers over the wire clientset, engages leader
election where the reference does (scheduler ``app/server.go:133``,
controller-manager ``controllermanager.go:107``), and shuts down
gracefully on SIGINT/SIGTERM."""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Callable, Optional

from .client.clientset import Clientset
from .client.leaderelection import LeaderElector
from .client.remote import RemoteStore

logger = logging.getLogger("kubernetes_tpu.daemon")


def remote_clientset(apiserver: Optional[str] = None,
                     token: Optional[str] = None,
                     kubeconfig: Optional[str] = None,
                     ca_file: Optional[str] = None,
                     client_cert: Optional[str] = None,
                     client_key: Optional[str] = None) -> Clientset:
    """Wire clientset from a server URL + token, or from a kubeconfig
    document (the kubeadm ``phases/kubeconfig`` artifact: server, CA pin,
    client cert/key, optional token).  Explicit args override the file.
    The single merge point for connection wiring — kubectl and every
    daemon share it, so a new kubeconfig field threads through once."""
    if kubeconfig:
        from .pki import load_kubeconfig

        doc = load_kubeconfig(kubeconfig)
        return Clientset(RemoteStore(
            apiserver or doc["server"],
            token=token or doc.get("token"),
            ca_file=ca_file or doc.get("certificate-authority"),
            client_cert=client_cert or doc.get("client-certificate"),
            client_key=client_key or doc.get("client-key"),
        ))
    return Clientset(RemoteStore(apiserver, token=token, ca_file=ca_file,
                                 client_cert=client_cert,
                                 client_key=client_key))


def install_signal_stop() -> threading.Event:
    """SIGINT/SIGTERM set the returned event (graceful shutdown)."""
    stop = threading.Event()

    def _handler(signum, frame):
        logger.info("signal %s: shutting down", signum)
        stop.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, _handler)
        except ValueError:  # non-main thread (tests)
            pass
    return stop


def run_with_leader_election(
    clientset: Clientset,
    lock_name: str,
    identity: str,
    run: Callable[[threading.Event], None],
    stop: threading.Event,
    retry_period: float = 2.0,
    leader_elect: bool = True,
) -> None:
    """RunOrDie (leaderelection.go:152): block until the lease is ours,
    run the payload in a thread, renew until lost or stopped.  Losing the
    lease stops the payload (the reference exits; standbys take over)."""
    if not leader_elect:
        run(stop)
        return
    elector = LeaderElector(clientset, lock_name, identity)
    while not stop.is_set():
        if not elector.try_acquire_or_renew():
            stop.wait(retry_period)
            continue
        logger.info("%s: became leader (%s)", lock_name, identity)
        lost = threading.Event()
        payload_stop = threading.Event()
        t = threading.Thread(target=run, args=(payload_stop,), daemon=True)
        t.start()
        while not stop.is_set():
            if not t.is_alive():
                # payload died: release so a standby takes over (the
                # reference exits the process here — same effect under a
                # supervisor); holding a lease while doing no work would
                # stall the whole control plane
                logger.error("%s: payload thread died; releasing lease", lock_name)
                elector.release()
                return
            if not elector.try_acquire_or_renew():
                logger.warning("%s: lost the lease", lock_name)
                lost.set()
                break
            stop.wait(elector.renew_deadline / 2)
        payload_stop.set()
        t.join(timeout=10)
        if not lost.is_set():
            elector.release()
            return
    # lease lost: loop back to standby (a real binary would exit; we
    # re-enter the acquire loop, which is equivalent under a supervisor)


def wait_forever(stop: threading.Event, tick: Optional[Callable[[], None]] = None,
                 interval: float = 1.0) -> None:
    while not stop.is_set():
        if tick is not None:
            tick()
        stop.wait(interval)


def serve_health(port: int, registry=None, host: str = "127.0.0.1"):
    """Daemon healthz + metrics + debug-trace endpoint (the reference
    mounts /healthz, /metrics and pprof on every daemon — scheduler
    app/server.go:149; /debug/traces is the pprof analogue for the wave
    tracer).  Must be started BEFORE leader election: a standby that
    serves no health endpoint gets killed by its supervisor's liveness
    probe.  Returns the running server (.local_port, .stop()), or None
    when port<0.

    ``/debug/traces`` serves the active tracer's Chrome trace-event JSON
    (load into chrome://tracing / Perfetto); ``/debug/flightrecorder``
    serves every dump the recorder has taken plus the current wave ring.
    Both answer ``{"enabled": false}`` when tracing is off — probing the
    endpoint must never perturb the production path."""
    from .proxy.healthcheck import _HealthHTTPServer

    if port is None or port < 0:
        return None

    class _DaemonHealth(_HealthHTTPServer):
        def handle(self, path: str):
            if path == "/healthz":
                return 200, {"status": "ok"}
            if path == "/metrics" and registry is not None:
                try:
                    return 200, registry.expose()  # raw exposition text
                except Exception as e:  # noqa: BLE001 - never crash health
                    return 500, {"error": str(e)}
            if path in ("/debug/traces", "/debug/flightrecorder"):
                from .utils import tracing

                tr = tracing.current()
                if tr is None:
                    return 200, {"enabled": False}
                try:
                    return 200, (tr.chrome_trace() if path == "/debug/traces"
                                 else tr.flight_snapshot())
                except Exception as e:  # noqa: BLE001 - never crash health
                    return 500, {"error": str(e)}
            return None

    server = _DaemonHealth(host=host, port=port)
    server.start()
    server.local_port = server.port
    return server
