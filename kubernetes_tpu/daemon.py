"""Shared daemon runtime: signals, leader election loop, serve-forever.

The reference ships eight runnable binaries (``cmd/*``,
``plugin/cmd/kube-scheduler``); the entry points here are their
process-model equivalent, started as::

    python -m kubernetes_tpu.apiserver   --port 6443 --token-file tokens
    python -m kubernetes_tpu.scheduler   --apiserver http://host:6443 --leader-elect
    python -m kubernetes_tpu.controllers --apiserver http://host:6443 --leader-elect
    python -m kubernetes_tpu.kubelet     --apiserver http://host:6443 --name n1 --proxy

Each wires threaded informers over the wire clientset, engages leader
election where the reference does (scheduler ``app/server.go:133``,
controller-manager ``controllermanager.go:107``), and shuts down
gracefully on SIGINT/SIGTERM."""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Callable, Optional

from .client.clientset import Clientset
from .client.leaderelection import LeaderElector
from .client.remote import RemoteStore

logger = logging.getLogger("kubernetes_tpu.daemon")


def remote_clientset(apiserver: Optional[str] = None,
                     token: Optional[str] = None,
                     kubeconfig: Optional[str] = None,
                     ca_file: Optional[str] = None,
                     client_cert: Optional[str] = None,
                     client_key: Optional[str] = None) -> Clientset:
    """Wire clientset from a server URL + token, or from a kubeconfig
    document (the kubeadm ``phases/kubeconfig`` artifact: server, CA pin,
    client cert/key, optional token).  Explicit args override the file.
    The single merge point for connection wiring — kubectl and every
    daemon share it, so a new kubeconfig field threads through once."""
    if kubeconfig:
        from .pki import load_kubeconfig

        doc = load_kubeconfig(kubeconfig)
        return Clientset(RemoteStore(
            apiserver or doc["server"],
            token=token or doc.get("token"),
            ca_file=ca_file or doc.get("certificate-authority"),
            client_cert=client_cert or doc.get("client-certificate"),
            client_key=client_key or doc.get("client-key"),
        ))
    return Clientset(RemoteStore(apiserver, token=token, ca_file=ca_file,
                                 client_cert=client_cert,
                                 client_key=client_key))


def install_signal_stop() -> threading.Event:
    """SIGINT/SIGTERM set the returned event (graceful shutdown)."""
    stop = threading.Event()

    def _handler(signum, frame):
        logger.info("signal %s: shutting down", signum)
        stop.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, _handler)
        except ValueError:  # non-main thread (tests)
            pass
    return stop


def run_with_leader_election(
    clientset: Clientset,
    lock_name: str,
    identity: str,
    run: Callable[[threading.Event], None],
    stop: threading.Event,
    retry_period: float = 2.0,
    leader_elect: bool = True,
) -> None:
    """RunOrDie (leaderelection.go:152): block until the lease is ours,
    run the payload in a thread, renew until lost or stopped.  Losing the
    lease stops the payload (the reference exits; standbys take over)."""
    if not leader_elect:
        run(stop)
        return
    elector = LeaderElector(clientset, lock_name, identity)
    while not stop.is_set():
        if not elector.try_acquire_or_renew():
            stop.wait(retry_period)
            continue
        logger.info("%s: became leader (%s)", lock_name, identity)
        lost = threading.Event()
        payload_stop = threading.Event()
        t = threading.Thread(target=run, args=(payload_stop,), daemon=True)
        t.start()
        while not stop.is_set():
            if not t.is_alive():
                # payload died: release so a standby takes over (the
                # reference exits the process here — same effect under a
                # supervisor); holding a lease while doing no work would
                # stall the whole control plane
                logger.error("%s: payload thread died; releasing lease", lock_name)
                elector.release()
                return
            if not elector.try_acquire_or_renew():
                logger.warning("%s: lost the lease", lock_name)
                lost.set()
                break
            stop.wait(elector.renew_deadline / 2)
        payload_stop.set()
        t.join(timeout=10)
        if not lost.is_set():
            elector.release()
            return
    # lease lost: loop back to standby (a real binary would exit; we
    # re-enter the acquire loop, which is equivalent under a supervisor)


def wait_forever(stop: threading.Event, tick: Optional[Callable[[], None]] = None,
                 interval: float = 1.0) -> None:
    while not stop.is_set():
        if tick is not None:
            tick()
        stop.wait(interval)


def telemetry_sink(spec: str):
    """``--telemetry-sink`` parsing, shared by every daemon: an
    ``http(s)://`` URL is a collector (the apiserver's ``/telemetry``
    ingest), anything else is a JSON-lines file path."""
    from .utils.telemetry import FileSink, HTTPSink

    if spec.startswith("http://") or spec.startswith("https://"):
        return HTTPSink(spec)
    return FileSink(spec)


def enable_continuous_telemetry(registry, interval_s: float = 1.0,
                                sink_spec: Optional[str] = None,
                                slos: bool = True):
    """One-call wiring for the continuous-telemetry stack, shared by
    every daemon ``__main__``: start the time-series scraper over
    ``registry``, attach the burn-rate SLO monitor (a breach fires the
    flight recorder), and — when a sink is given — the off-box shipper
    fed with flight dumps (via the recorder's dump hook) and per-scrape
    time-series deltas.  Returns the store (``timeseries.disable()`` /
    ``telemetry.disable()`` tear the stack down)."""
    from .utils import slo, telemetry, timeseries

    store = timeseries.enable(registry, interval_s=interval_s)
    if slos:
        slo.monitor(store=store)
    if sink_spec:
        shipper = telemetry.enable(telemetry_sink(sink_spec),
                                   registry=registry)
        store.add_observer(telemetry.timeseries_observer(shipper))
    return store


def serve_health(port: int, registry=None, host: str = "127.0.0.1"):
    """Daemon healthz + metrics + debug endpoints (the reference mounts
    /healthz, /metrics and pprof on every daemon — scheduler
    app/server.go:149; /debug/traces is the pprof analogue for the wave
    tracer).  Must be started BEFORE leader election: a standby that
    serves no health endpoint gets killed by its supervisor's liveness
    probe.  Returns the running server (.local_port, .stop()), or None
    when port<0.

    The route set is the shared :mod:`kubernetes_tpu.utils.health`
    contract — identical on every daemon: ``/healthz``, ``/metrics``,
    ``/debug/traces``, ``/debug/flightrecorder``, ``/debug/timeseries``.
    Disabled subsystems answer ``{"enabled": false}`` — probing an
    endpoint must never perturb the production path."""
    from .proxy.healthcheck import _HealthHTTPServer
    from .utils.health import DebugRoutesMixin

    if port is None or port < 0:
        return None

    class _DaemonHealth(DebugRoutesMixin, _HealthHTTPServer):
        pass

    server = _DaemonHealth(host=host, port=port)
    server.registry = registry
    server.start()
    server.local_port = server.port
    return server
