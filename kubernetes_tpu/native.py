"""ctypes bridge to the native C++ engines in ``csrc/``.

Builds ``liblabelmatch.so`` with g++ on first use (cached next to the
sources); every consumer falls back to the pure-Python implementation when
the toolchain is unavailable, so the framework never hard-depends on the
native layer — it just gets faster with it (SURVEY.md §7.1's split: Python
wiring, compiled hot loops)."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence

logger = logging.getLogger("kubernetes_tpu.native")

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc")
_SO_PATH = os.path.join(_CSRC, "liblabelmatch.so")
_SRC_PATH = os.path.join(_CSRC, "labelmatch.cpp")

_lib = None
_lib_mu = threading.Lock()
_build_failed = False
# finalizer close failures (ktpu-analyze CH702): __del__ may run during
# interpreter teardown where logging is unsafe — count, never log there
_del_close_failures = 0

OP_IN, OP_NOT_IN, OP_EXISTS, OP_DOES_NOT_EXIST, OP_GT, OP_LT, OP_EQ = range(7)
_OP_BY_NAME = {
    "In": OP_IN,
    "NotIn": OP_NOT_IN,
    "Exists": OP_EXISTS,
    "DoesNotExist": OP_DOES_NOT_EXIST,
    "Gt": OP_GT,
    "Lt": OP_LT,
}


def _build() -> Optional[str]:
    return _compile_cached(
        _SRC_PATH, _SO_PATH,
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC_PATH],
    )


def _compile_cached(src: str, so: str, cmd: list[str]) -> Optional[str]:
    """Compile ``src`` to ``so`` if stale; atomic rename so concurrent
    processes never observe a half-written library.  Shared by every
    native component."""
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    tmp = f"{so}.{os.getpid()}.tmp"
    try:
        subprocess.run(cmd + ["-o", tmp], check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return so
    except Exception as e:  # noqa: BLE001 - any failure -> Python fallback
        logger.warning("native build of %s failed (%s); using Python fallback", src, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


# -- fastcopy: native deep copy of JSON-shaped data ------------------------
_FC_SRC = os.path.join(os.path.dirname(_SRC_PATH), "fastcopy.c")
_fc_fn = None
_fc_failed = False
_fc_mu = threading.Lock()


def _fc_so_path() -> str:
    # keyed on the interpreter ABI: this library calls CPython APIs, so a
    # cached build from another Python version must never be loaded
    import sysconfig

    tag = sysconfig.get_config_var("SOABI") or "py"
    return os.path.join(os.path.dirname(_SRC_PATH), f"libfastcopy-{tag}.so")


def get_fastcopy():
    """The native deepcopy callable (PyObject -> PyObject), or None.
    Built with the Python C API and loaded via ctypes.PyDLL (GIL held);
    undefined CPython symbols resolve against the running interpreter."""
    global _fc_fn, _fc_failed
    with _fc_mu:
        if _fc_fn is not None or _fc_failed:
            return _fc_fn
        try:
            import sysconfig

            include = sysconfig.get_paths()["include"]
            so = _compile_cached(
                _FC_SRC, _fc_so_path(),
                ["gcc", "-O2", "-shared", "-fPIC", f"-I{include}", _FC_SRC],
            )
            if so is None:
                raise RuntimeError("compile failed")
            lib = ctypes.PyDLL(so)
            lib.fc_deepcopy.restype = ctypes.py_object
            lib.fc_deepcopy.argtypes = [ctypes.py_object]
            fn = lib.fc_deepcopy
            # self-check before trusting it on the store's hot path (an
            # explicit raise: asserts vanish under PYTHONOPTIMIZE)
            probe = {"a": [1, {"b": "c"}], "d": None}
            got = fn(probe)
            if not (
                got == probe
                and got is not probe
                and got["a"] is not probe["a"]
                and got["a"][1] is not probe["a"][1]
            ):
                raise RuntimeError("fastcopy self-check failed")
            _fc_fn = fn
        except Exception as e:  # noqa: BLE001 - any failure -> Python fallback
            logger.warning("native fastcopy unavailable (%s); using Python fallback", e)
            _fc_failed = True
        return _fc_fn


def get_lib():
    """The loaded native library, or None (Python fallback)."""
    global _lib, _build_failed
    with _lib_mu:
        if _lib is not None or _build_failed:
            return _lib
        so = _build()
        if so is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(so)
        lib.lm_new.restype = ctypes.c_void_p
        lib.lm_free.argtypes = [ctypes.c_void_p]
        lib.lm_add_labelmap.restype = ctypes.c_int32
        lib.lm_add_labelmap.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int32,
        ]
        lib.lm_new_selector.restype = ctypes.c_int32
        lib.lm_new_selector.argtypes = [ctypes.c_void_p]
        lib.lm_sel_add_req.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int32,
        ]
        lib.lm_match_matrix.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.lm_match_any.argtypes = list(lib.lm_match_matrix.argtypes)
        _lib = lib
        return _lib


def _carr_str(items: Sequence[str]):
    arr = (ctypes.c_char_p * max(len(items), 1))()
    for i, s in enumerate(items):
        arr[i] = s.encode()
    return arr


class MatchEngine:
    """Interned selector/labelmap matcher; transparently native or Python."""

    def __init__(self):
        self._lib = get_lib()
        self._h = self._lib.lm_new() if self._lib else None
        # python fallback state
        self._py_labelmaps: list[dict] = []
        self._py_selectors: list[list] = []

    def close(self) -> None:
        if self._lib and self._h:
            self._lib.lm_free(self._h)
            self._h = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:  # noqa: BLE001 - teardown: logging is unsafe here
            global _del_close_failures
            _del_close_failures += 1

    @property
    def native(self) -> bool:
        return self._h is not None

    # -- registration ------------------------------------------------------
    def add_labelmap(self, labels: dict) -> int:
        if self._h:
            keys = _carr_str(list(labels.keys()))
            vals = _carr_str([str(v) for v in labels.values()])
            return self._lib.lm_add_labelmap(self._h, keys, vals, len(labels))
        self._py_labelmaps.append(dict(labels))
        return len(self._py_labelmaps) - 1

    def add_selector(self, requirements: list[tuple[str, str, list[str]]]) -> int:
        """requirements: [(key, op_name, values)]; op "Eq" = key=value."""
        if self._h:
            sid = self._lib.lm_new_selector(self._h)
            for key, op_name, values in requirements:
                op = OP_EQ if op_name == "Eq" else _OP_BY_NAME[op_name]
                self._lib.lm_sel_add_req(
                    self._h, sid, key.encode(), op, _carr_str(values), len(values)
                )
            return sid
        self._py_selectors.append(list(requirements))
        return len(self._py_selectors) - 1

    def add_simple_selector(self, selector: dict) -> int:
        return self.add_selector([(k, "Eq", [str(v)]) for k, v in selector.items()])

    def add_label_selector(self, sel) -> int:
        """From an api.selectors.LabelSelector."""
        reqs = [(k, "Eq", [str(v)]) for k, v in sel.match_labels.items()]
        reqs += [(r.key, r.operator, list(r.values)) for r in sel.match_expressions]
        return self.add_selector(reqs)

    # -- matching ----------------------------------------------------------
    def match_matrix(self, selector_ids: Sequence[int], labelmap_ids: Sequence[int]):
        import numpy as np

        ns, nl = len(selector_ids), len(labelmap_ids)
        out = np.zeros((ns, nl), dtype=np.uint8)
        if ns == 0 or nl == 0:
            return out.astype(bool)
        if self._h:
            sarr = (ctypes.c_int32 * ns)(*selector_ids)
            larr = (ctypes.c_int32 * nl)(*labelmap_ids)
            self._lib.lm_match_matrix(
                self._h, sarr, ns, larr, nl, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            )
            return out.astype(bool)
        for i, sid in enumerate(selector_ids):
            for j, lid in enumerate(labelmap_ids):
                out[i, j] = self._py_match(sid, lid)
        return out.astype(bool)

    def match_any(self, selector_ids: Sequence[int], labelmap_ids: Sequence[int]):
        import numpy as np

        nl = len(labelmap_ids)
        out = np.zeros(nl, dtype=np.uint8)
        if nl == 0 or len(selector_ids) == 0:
            return out.astype(bool)
        if self._h:
            sarr = (ctypes.c_int32 * len(selector_ids))(*selector_ids)
            larr = (ctypes.c_int32 * nl)(*labelmap_ids)
            self._lib.lm_match_any(
                self._h, sarr, len(selector_ids), larr, nl,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
            return out.astype(bool)
        for j, lid in enumerate(labelmap_ids):
            out[j] = any(self._py_match(sid, lid) for sid in selector_ids)
        return out.astype(bool)

    # -- python fallback ---------------------------------------------------
    def _py_match(self, sid: int, lid: int) -> bool:
        from .api.selectors import Requirement

        labels = self._py_labelmaps[lid]
        for key, op_name, values in self._py_selectors[sid]:
            if op_name == "Eq":
                if labels.get(key) != values[0]:
                    return False
            elif not Requirement(key, op_name, list(values)).matches(labels):
                return False
        return True


# -- pause: the per-pod infrastructure binary -------------------------------
# (reference build/pause/pause.c — reaps zombies, exits on TERM, sleeps)
_PAUSE_SRC = os.path.join(_CSRC, "pause.c")
_PAUSE_BIN = os.path.join(_CSRC, "ktpu-pause")
_pause_failed = False


def pause_binary() -> Optional[str]:
    """Path to the compiled pause binary, building on first use; None if
    no C toolchain is available (sandboxes then stay process-less).
    Failure is memoized like the other native components — a 5k-node
    fleet must not re-spawn a failing compiler per kubelet."""
    global _pause_failed
    if _pause_failed:
        return None
    out = _compile_cached(
        _PAUSE_SRC, _PAUSE_BIN, ["gcc", "-O2", "-static", _PAUSE_SRC]
    ) or _compile_cached(
        # -static can fail where no static libc is installed
        _PAUSE_SRC, _PAUSE_BIN, ["gcc", "-O2", _PAUSE_SRC]
    )
    if out is None:
        _pause_failed = True
    return out
