"""Authentication (SURVEY.md §2.9): who is making this request.

Capability equivalents of the reference's authenticator stack
(``pkg/kubeapiserver/authenticator/config.go`` builds a union of x509,
token-file, service-account-JWT, bootstrap-token and webhook
authenticators; interfaces in ``apiserver/pkg/authentication``).

Transport note: the reference's x509 path authenticates the TLS client
cert; this server speaks plain HTTP in-proc, so every credential rides the
``Authorization`` header and identity-asserting headers play the role of
client certs (the reference itself has this shape as the front-proxy
``RequestHeader`` authenticator).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class UserInfo:
    """Reference ``authentication/user.Info``."""

    name: str = ""
    groups: list[str] = field(default_factory=list)

    @property
    def authenticated(self) -> bool:
        return bool(self.name) and self.name != ANONYMOUS.name


ANONYMOUS = UserInfo(name="system:anonymous", groups=["system:unauthenticated"])


class Authenticator:
    """Returns a UserInfo or None (not my credential type / invalid)."""

    def authenticate(self, headers) -> Optional[UserInfo]:
        raise NotImplementedError


class TokenFileAuthenticator(Authenticator):
    """Static bearer tokens (reference ``--token-auth-file``,
    ``plugin/pkg/auth/authenticator/token/tokenfile``)."""

    def __init__(self, tokens: dict[str, UserInfo | str]):
        self.tokens: dict[str, UserInfo] = {
            t: (u if isinstance(u, UserInfo) else UserInfo(name=u))
            for t, u in tokens.items()
        }

    def authenticate(self, headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        return self.tokens.get(auth[7:])


class BootstrapTokenAuthenticator(Authenticator):
    """Bootstrap tokens "<id>.<secret>" validated against live
    ``bootstrap-token-<id>`` Secrets in kube-system (reference
    ``plugin/pkg/auth/authenticator/token/bootstrap``): unexpired tokens
    authenticate as ``system:bootstrap:<id>`` in
    ``system:bootstrappers`` — the kubeadm join credential."""

    def __init__(self, store, clock=None):
        import time

        self.store = store
        self.clock = clock or time.time

    def authenticate(self, headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer ") or "." not in auth[7:]:
            return None
        token_id, _, token_secret = auth[7:].partition(".")
        from ..store.store import NotFoundError

        try:
            raw = self.store.get("Secret", "kube-system", f"bootstrap-token-{token_id}")
        except NotFoundError:
            return None
        import hmac as _hmac

        from ..controllers.ipam import parse_token_expiration

        data = raw.get("data") or {}
        if not _hmac.compare_digest(
            str(data.get("token-secret", "")), token_secret
        ):
            return None
        if parse_token_expiration(data.get("expiration")) <= self.clock():
            return None
        # the reference splits token usages: a signing-only token must NOT
        # authenticate — require the authentication usage explicitly
        if data.get("usage-bootstrap-authentication") not in ("true", True):
            return None
        return UserInfo(name=f"system:bootstrap:{token_id}",
                        groups=["system:bootstrappers"])


class RequestHeaderAuthenticator(Authenticator):
    """Identity asserted via X-Remote-User / X-Remote-Group headers — the
    front-proxy / client-cert stand-in (reference
    ``apiserver/pkg/authentication/request/headerrequest``)."""

    def authenticate(self, headers) -> Optional[UserInfo]:
        name = headers.get("X-Remote-User", "")
        if not name:
            return None
        groups = [g for g in headers.get("X-Remote-Group", "").split(",") if g]
        return UserInfo(name=name, groups=groups)


class ServiceAccountTokenAuthenticator(Authenticator):
    """Verifies tokens minted by :class:`ServiceAccountTokenMinter`
    (reference ``pkg/serviceaccount/jwt.go`` — JWTs signed with the cluster
    key; here HMAC-SHA256 in JWT layout, no external deps)."""

    def __init__(self, minter: "ServiceAccountTokenMinter"):
        self.minter = minter

    def authenticate(self, headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        claims = self.minter.verify(auth[7:])
        if claims is None:
            return None
        namespace, name = claims
        return UserInfo(
            name=f"system:serviceaccount:{namespace}:{name}",
            groups=["system:serviceaccounts", f"system:serviceaccounts:{namespace}"],
        )


class UnionAuthenticator(Authenticator):
    """First authenticator that recognizes the credential wins (reference
    ``authentication/request/union``)."""

    def __init__(self, *authenticators: Authenticator, allow_anonymous: bool = True):
        self.authenticators = list(authenticators)
        self.allow_anonymous = allow_anonymous

    def authenticate(self, headers) -> Optional[UserInfo]:
        for a in self.authenticators:
            user = a.authenticate(headers)
            if user is not None:
                return user
        # A credential that is PRESENT but unrecognized fails with 401; it
        # must not be downgraded to anonymous (the reference rejects
        # malformed/unknown bearer tokens rather than treating the request
        # as unauthenticated).
        if headers.get("Authorization", ""):
            return None
        return ANONYMOUS if self.allow_anonymous else None


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def kubelet_exec_token(node_name: str, key: bytes = b"cluster-signing-key") -> str:
    """The control plane's credential for a node's exec endpoint: HMAC of
    the node name under the cluster signing key.  Only components holding
    the key (apiserver, kubectl pointed at the in-proc store) can mint it
    — reading node.status alone is not enough to run commands (the
    reference's kubelet delegated-authz contract, minimally)."""
    return hmac.new(key, f"kubelet-exec:{node_name}".encode(), hashlib.sha256).hexdigest()


class ServiceAccountTokenMinter:
    """Mints and verifies service-account bearer tokens (reference
    ``pkg/serviceaccount`` TokenGenerator; the controller writes them into
    token Secrets)."""

    def __init__(self, signing_key: bytes = b"cluster-signing-key"):
        self.key = signing_key

    def mint(self, namespace: str, name: str) -> str:
        header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = _b64(json.dumps({
            "sub": f"system:serviceaccount:{namespace}:{name}",
            "kubernetes.io/serviceaccount/namespace": namespace,
            "kubernetes.io/serviceaccount/service-account.name": name,
        }).encode())
        sig = _b64(hmac.new(self.key, f"{header}.{payload}".encode(), hashlib.sha256).digest())
        return f"{header}.{payload}.{sig}"

    def verify(self, token: str) -> Optional[tuple[str, str]]:
        try:
            header, payload, sig = token.split(".")
            expect = _b64(hmac.new(self.key, f"{header}.{payload}".encode(), hashlib.sha256).digest())
            if not hmac.compare_digest(sig, expect):
                return None
            claims = json.loads(_unb64(payload))
            return (
                claims["kubernetes.io/serviceaccount/namespace"],
                claims["kubernetes.io/serviceaccount/service-account.name"],
            )
        except (ValueError, KeyError):
            return None
