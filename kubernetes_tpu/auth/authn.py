"""Authentication (SURVEY.md §2.9): who is making this request.

Capability equivalents of the reference's authenticator stack
(``pkg/kubeapiserver/authenticator/config.go`` builds a union of x509,
token-file, service-account-JWT, bootstrap-token and webhook
authenticators; interfaces in ``apiserver/pkg/authentication``).

Transport note: the reference's x509 path authenticates the TLS client
cert; this server speaks plain HTTP in-proc, so every credential rides the
``Authorization`` header and identity-asserting headers play the role of
client certs (the reference itself has this shape as the front-proxy
``RequestHeader`` authenticator).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
from dataclasses import dataclass, field
from typing import Optional

# rejection causes log at debug and never include credential material —
# auth failures are normal traffic, but a systematic one needs a trail
logger = logging.getLogger("kubernetes_tpu.auth")


@dataclass
class UserInfo:
    """Reference ``authentication/user.Info``."""

    name: str = ""
    groups: list[str] = field(default_factory=list)

    @property
    def authenticated(self) -> bool:
        return bool(self.name) and self.name != ANONYMOUS.name


ANONYMOUS = UserInfo(name="system:anonymous", groups=["system:unauthenticated"])


class Authenticator:
    """Returns a UserInfo or None (not my credential type / invalid)."""

    def authenticate(self, headers) -> Optional[UserInfo]:
        raise NotImplementedError


class TokenFileAuthenticator(Authenticator):
    """Static bearer tokens (reference ``--token-auth-file``,
    ``plugin/pkg/auth/authenticator/token/tokenfile``)."""

    def __init__(self, tokens: dict[str, UserInfo | str]):
        self.tokens: dict[str, UserInfo] = {
            t: (u if isinstance(u, UserInfo) else UserInfo(name=u))
            for t, u in tokens.items()
        }

    def authenticate(self, headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        return self.tokens.get(auth[7:])


class BootstrapTokenAuthenticator(Authenticator):
    """Bootstrap tokens "<id>.<secret>" validated against live
    ``bootstrap-token-<id>`` Secrets in kube-system (reference
    ``plugin/pkg/auth/authenticator/token/bootstrap``): unexpired tokens
    authenticate as ``system:bootstrap:<id>`` in
    ``system:bootstrappers`` — the kubeadm join credential."""

    def __init__(self, store, clock=None):
        import time

        self.store = store
        self.clock = clock or time.time

    def authenticate(self, headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer ") or "." not in auth[7:]:
            return None
        token_id, _, token_secret = auth[7:].partition(".")
        from ..store.store import NotFoundError

        try:
            raw = self.store.get("Secret", "kube-system", f"bootstrap-token-{token_id}")
        except NotFoundError:
            return None
        import hmac as _hmac

        from ..controllers.ipam import parse_token_expiration

        data = raw.get("data") or {}
        if not _hmac.compare_digest(
            str(data.get("token-secret", "")), token_secret
        ):
            return None
        if parse_token_expiration(data.get("expiration")) <= self.clock():
            return None
        # the reference splits token usages: a signing-only token must NOT
        # authenticate — require the authentication usage explicitly
        if data.get("usage-bootstrap-authentication") not in ("true", True):
            return None
        return UserInfo(name=f"system:bootstrap:{token_id}",
                        groups=["system:bootstrappers"])


class RequestHeaderAuthenticator(Authenticator):
    """Identity asserted via X-Remote-User / X-Remote-Group headers — the
    front-proxy / client-cert stand-in (reference
    ``apiserver/pkg/authentication/request/headerrequest``)."""

    def authenticate(self, headers) -> Optional[UserInfo]:
        name = headers.get("X-Remote-User", "")
        if not name:
            return None
        groups = [g for g in headers.get("X-Remote-Group", "").split(",") if g]
        return UserInfo(name=name, groups=groups)


class ServiceAccountTokenAuthenticator(Authenticator):
    """Verifies tokens minted by :class:`ServiceAccountTokenMinter`
    (reference ``pkg/serviceaccount/jwt.go`` — JWTs signed with the cluster
    key; here HMAC-SHA256 in JWT layout, no external deps)."""

    def __init__(self, minter: "ServiceAccountTokenMinter"):
        self.minter = minter

    def authenticate(self, headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        claims = self.minter.verify(auth[7:])
        if claims is None:
            return None
        namespace, name = claims
        return UserInfo(
            name=f"system:serviceaccount:{namespace}:{name}",
            groups=["system:serviceaccounts", f"system:serviceaccounts:{namespace}"],
        )


class X509CertificateAuthenticator(Authenticator):
    """Client-certificate identity (reference
    ``apiserver/pkg/authentication/request/x509``): subject CN is the
    user, O entries are the groups.

    Two ingestion paths, both ending in the same subject mapping:

    - **TLS handshake** (the reference's own path): the wire server
      verifies the chain against the client CA during the handshake and
      hands the peer-cert subject to :meth:`from_peercert`.
    - **PEM header** (front-proxy style, for plain-HTTP deployments): the
      proxy forwards the client cert in ``X-Client-Certificate``
      (base64 PEM); :meth:`authenticate` verifies the CA signature and
      validity window before trusting the subject.  Because a certificate
      alone proves nothing about who SENT it (certs are public artifacts),
      this path additionally requires the proxy to authenticate itself
      with ``proxy_secret`` in ``X-Proxy-Authorization`` — the analogue of
      the reference requiring the front proxy's own client cert
      (``--requestheader-client-ca-file``).  Without a configured
      ``proxy_secret`` the header path is disabled entirely.
    """

    HEADER = "X-Client-Certificate"
    PROXY_HEADER = "X-Proxy-Authorization"

    def __init__(self, ca_pem: Optional[bytes] = None,
                 proxy_secret: Optional[str] = None, clock=None):
        import time

        self.ca_pem = ca_pem
        self.proxy_secret = proxy_secret
        self.clock = clock or time.time

    @staticmethod
    def from_peercert(peercert: Optional[dict]) -> Optional[UserInfo]:
        """Map an ``ssl.SSLSocket.getpeercert()`` dict (chain already
        verified by the handshake) to a UserInfo."""
        if not peercert:
            return None
        name, groups = "", []
        for rdn in peercert.get("subject", ()):
            for key, value in rdn:
                if key == "commonName":
                    name = value
                elif key == "organizationName":
                    groups.append(value)
        return UserInfo(name=name, groups=groups) if name else None

    def authenticate(self, headers) -> Optional[UserInfo]:
        raw = headers.get(self.HEADER, "")
        if not raw or self.ca_pem is None or not self.proxy_secret:
            return None
        if not hmac.compare_digest(
            headers.get(self.PROXY_HEADER, ""), self.proxy_secret
        ):
            return None
        try:
            pem = _unb64(raw)
        except Exception as e:  # noqa: BLE001 - bad credential => 401
            logger.debug("x509: undecodable %s payload (%s): rejected",
                         self.HEADER, type(e).__name__)
            return None
        return self._verify_pem(pem)

    def _verify_pem(self, pem: bytes) -> Optional[UserInfo]:
        try:
            from cryptography import x509 as cx509
            from cryptography.x509.oid import NameOID

            cert = cx509.load_pem_x509_certificate(pem)
            ca = cx509.load_pem_x509_certificate(self.ca_pem)
            cert.verify_directly_issued_by(ca)
        except Exception as e:  # noqa: BLE001 - bad credential => 401
            # unparseable cert, signature mismatch, or no cryptography
            # module at all — every case reads as a rejected credential
            logger.debug("x509: certificate verification failed (%s): "
                         "rejected", type(e).__name__)
            return None
        import datetime

        now = datetime.datetime.fromtimestamp(self.clock(), tz=datetime.timezone.utc)
        if not (cert.not_valid_before_utc <= now <= cert.not_valid_after_utc):
            return None
        cn = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
        orgs = cert.subject.get_attributes_for_oid(NameOID.ORGANIZATION_NAME)
        if not cn:
            return None
        return UserInfo(name=cn[0].value, groups=[o.value for o in orgs])


class WebhookTokenAuthenticator(Authenticator):
    """Delegates bearer tokens to an external TokenReview service
    (reference ``plugin/pkg/auth/authenticator/token/webhook``): POST a
    TokenReview, trust the returned user on ``status.authenticated``.
    Verdicts are cached with a TTL (the reference's 2-minute cache) so a
    flood of requests doesn't hammer the webhook."""

    CACHE_MAX = 4096

    def __init__(self, url: str, timeout: float = 5.0, cache_ttl: float = 120.0,
                 clock=None):
        import time

        self.url = url
        self.timeout = timeout
        self.cache_ttl = cache_ttl
        self.clock = clock or time.time
        self._cache: dict[str, tuple[float, Optional[UserInfo]]] = {}

    def authenticate(self, headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        token = auth[7:]
        hit = self._cache.get(token)
        if hit is not None and self.clock() - hit[0] < self.cache_ttl:
            return hit[1]
        try:
            user = self._review(token)
        except OSError:
            # transport failure is NOT a verdict: don't poison the cache —
            # the token gets re-reviewed as soon as the webhook recovers
            return None
        now = self.clock()
        if len(self._cache) >= self.CACHE_MAX:
            # evict expired entries; if still full (an unauthenticated
            # flood of distinct tokens), drop the oldest — the cache must
            # not be a memory-exhaustion vector
            self._cache = {t: v for t, v in self._cache.items()
                           if now - v[0] < self.cache_ttl}
            while len(self._cache) >= self.CACHE_MAX:
                self._cache.pop(next(iter(self._cache)))
        self._cache[token] = (now, user)
        return user

    def _review(self, token: str) -> Optional[UserInfo]:
        import urllib.error
        import urllib.request

        body = json.dumps({"kind": "TokenReview",
                           "spec": {"token": token}}).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                status = json.loads(r.read()).get("status") or {}
        except urllib.error.HTTPError as e:
            if e.code < 500:
                # a 4xx is a deliberate answer: fail closed, cacheable
                return None
            # a 5xx is the webhook failing, not deciding — treat like an
            # unreachable server so the verdict cache is not poisoned
            raise OSError(f"webhook 5xx: {e.code}") from e
        except Exception as e:
            # unreachable/timeout: fail closed for this request but let the
            # caller skip the cache write
            raise OSError(str(e)) from e
        if not status.get("authenticated"):
            return None
        user = status.get("user") or {}
        if not user.get("username"):
            return None
        return UserInfo(name=user["username"], groups=list(user.get("groups") or []))


class OIDCAuthenticator(Authenticator):
    """OIDC-style JWT validation (reference
    ``plugin/pkg/auth/authenticator/token/oidc``): verify signature,
    issuer, audience and expiry, then map the username/groups claims.
    Verification keys are supplied out-of-band (the reference fetches
    JWKS from the issuer; this deployment has no egress, so the key is
    config): HS256 with a shared secret, or RS256 with an RSA public key
    when the ``cryptography`` backend is present."""

    def __init__(self, issuer: str, audience: str, key,
                 username_claim: str = "sub", groups_claim: str = "groups",
                 username_prefix: str = "", alg: Optional[str] = None,
                 clock=None):
        import time

        self.issuer = issuer
        self.audience = audience
        self.key = key
        self.username_claim = username_claim
        self.groups_claim = groups_claim
        self.username_prefix = username_prefix
        # The accepted algorithm is FIXED at configuration time — never
        # taken from the token header, or an attacker could downgrade an
        # RS256 deployment to HS256 and use the (public!) RSA key PEM as
        # the HMAC secret to forge identities.
        if alg is None:
            key_bytes = key if isinstance(key, (bytes, str)) else None
            if key_bytes is not None:
                kb = key_bytes if isinstance(key_bytes, bytes) else key_bytes.encode()
                alg = "RS256" if kb.lstrip().startswith(b"-----BEGIN") else "HS256"
            else:
                alg = "RS256"  # loaded public-key object
        self.alg = alg
        self.clock = clock or time.time

    def authenticate(self, headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer ") or auth.count(".") != 2:
            return None
        token = auth[7:]
        try:
            h64, p64, s64 = token.split(".")
            header = json.loads(_unb64(h64))
            claims = json.loads(_unb64(p64))
            sig = _unb64(s64)
            if not isinstance(header, dict) or not isinstance(claims, dict):
                return None
            # issuer gate FIRST: a token from another issuer is "not my
            # credential type" and must fall through in a union
            if claims.get("iss") != self.issuer:
                return None
            if header.get("alg") != self.alg:
                return None
            if not self._verify_sig(self.alg, f"{h64}.{p64}".encode(), sig):
                return None
            aud = claims.get("aud")
            if self.audience not in (aud if isinstance(aud, list) else [aud]):
                return None
            # exp is MANDATORY (OIDC Core requires it in ID tokens): a
            # token without one would be valid forever and can never be
            # invalidated
            if "exp" not in claims or float(claims["exp"]) <= self.clock():
                return None
            name = claims.get(self.username_claim, "")
            if not name:
                return None
            groups = claims.get(self.groups_claim) or []
            if isinstance(groups, str):
                groups = [groups]
            return UserInfo(name=self.username_prefix + str(name),
                            groups=[str(g) for g in groups])
        except Exception as e:  # noqa: BLE001
            # malformed claims must read as a bad credential (401), never
            # crash the request thread
            logger.debug("oidc: malformed token/claims (%s): rejected",
                         type(e).__name__)
            return None

    def _verify_sig(self, alg: str, signed: bytes, sig: bytes) -> bool:
        if alg == "HS256" and isinstance(self.key, (bytes, str)):
            key = self.key if isinstance(self.key, bytes) else self.key.encode()
            return hmac.compare_digest(
                sig, hmac.new(key, signed, hashlib.sha256).digest())
        if alg == "RS256":
            try:
                from cryptography.hazmat.primitives import hashes, serialization
                from cryptography.hazmat.primitives.asymmetric import padding

                key = self.key
                if isinstance(key, (bytes, str)):
                    pem = key if isinstance(key, bytes) else key.encode()
                    key = serialization.load_pem_public_key(pem)
                key.verify(sig, signed, padding.PKCS1v15(), hashes.SHA256())
                return True
            except Exception:
                return False
        return False


class UnionAuthenticator(Authenticator):
    """First authenticator that recognizes the credential wins (reference
    ``authentication/request/union``)."""

    def __init__(self, *authenticators: Authenticator, allow_anonymous: bool = True):
        self.authenticators = list(authenticators)
        self.allow_anonymous = allow_anonymous

    def authenticate(self, headers) -> Optional[UserInfo]:
        for a in self.authenticators:
            user = a.authenticate(headers)
            if user is not None:
                return user
        # A credential that is PRESENT but unrecognized fails with 401; it
        # must not be downgraded to anonymous (the reference rejects
        # malformed/unknown bearer tokens rather than treating the request
        # as unauthenticated).
        if headers.get("Authorization", ""):
            return None
        return ANONYMOUS if self.allow_anonymous else None


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


# the cluster's signing key: ONE definition — every node-scoped HMAC
# credential (exec, tunnel, SA tokens) defaults to it, so a configurable
# key can never drift between the minting sites
CLUSTER_SIGNING_KEY = b"cluster-signing-key"


def kubelet_exec_token(node_name: str, key: bytes = CLUSTER_SIGNING_KEY) -> str:
    """The control plane's credential for a node's exec endpoint: HMAC of
    the node name under the cluster signing key.  Only components holding
    the key (apiserver, kubectl pointed at the in-proc store) can mint it
    — reading node.status alone is not enough to run commands (the
    reference's kubelet delegated-authz contract, minimally)."""
    return hmac.new(key, f"kubelet-exec:{node_name}".encode(), hashlib.sha256).hexdigest()


class ServiceAccountTokenMinter:
    """Mints and verifies service-account bearer tokens (reference
    ``pkg/serviceaccount`` TokenGenerator; the controller writes them into
    token Secrets)."""

    def __init__(self, signing_key: bytes = CLUSTER_SIGNING_KEY):
        self.key = signing_key

    def mint(self, namespace: str, name: str) -> str:
        header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = _b64(json.dumps({
            "sub": f"system:serviceaccount:{namespace}:{name}",
            "kubernetes.io/serviceaccount/namespace": namespace,
            "kubernetes.io/serviceaccount/service-account.name": name,
        }).encode())
        sig = _b64(hmac.new(self.key, f"{header}.{payload}".encode(), hashlib.sha256).digest())
        return f"{header}.{payload}.{sig}"

    def verify(self, token: str) -> Optional[tuple[str, str]]:
        try:
            header, payload, sig = token.split(".")
            expect = _b64(hmac.new(self.key, f"{header}.{payload}".encode(), hashlib.sha256).digest())
            if not hmac.compare_digest(sig, expect):
                return None
            claims = json.loads(_unb64(payload))
            return (
                claims["kubernetes.io/serviceaccount/namespace"],
                claims["kubernetes.io/serviceaccount/service-account.name"],
            )
        except (ValueError, KeyError):
            return None
