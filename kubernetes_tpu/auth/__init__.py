"""AuthN/AuthZ/audit stack (SURVEY.md §2.9, §5.5)."""

from .audit import (
    AuditEvent,
    AuditPolicy,
    Auditor,
    LogBackend,
    MemoryBackend,
    WebhookBackend,
)
from .audit import PolicyRule as AuditPolicyRule
from .authn import (
    BootstrapTokenAuthenticator,
    ANONYMOUS,
    Authenticator,
    OIDCAuthenticator,
    RequestHeaderAuthenticator,
    ServiceAccountTokenAuthenticator,
    ServiceAccountTokenMinter,
    TokenFileAuthenticator,
    UnionAuthenticator,
    UserInfo,
    WebhookTokenAuthenticator,
    X509CertificateAuthenticator,
)
from .authz import (
    ALLOW,
    DENY,
    NO_OPINION,
    ABACAuthorizer,
    AlwaysAllow,
    AuthenticatedOrDiscovery,
    AuthzAttributes,
    Authorizer,
    BootstrapPolicyAuthorizer,
    NodeAuthorizer,
    RBACAuthorizer,
    UnionAuthorizer,
    WebhookAuthorizer,
)
