"""Audit pipeline (SURVEY.md §5.5 — ``apiserver/pkg/audit`` + policy in
``pkg/apis/audit``): one structured event per request stage, filtered by a
policy, delivered to pluggable backends; wired as a request filter in the
apiserver (``server/config.go:474``)."""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

# audit levels (reference audit policy)
NONE = "None"
METADATA = "Metadata"
REQUEST = "Request"
REQUEST_RESPONSE = "RequestResponse"

_LEVELS = [NONE, METADATA, REQUEST, REQUEST_RESPONSE]


@dataclass
class AuditEvent:
    """Reference ``audit.Event`` at the depth the filter records."""

    stage: str  # RequestReceived | ResponseComplete
    user: str
    verb: str
    resource: str
    namespace: str
    name: str
    code: int = 0
    request_object: Optional[dict] = None
    timestamp: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        d = {
            "stage": self.stage,
            "user": self.user,
            "verb": self.verb,
            "resource": self.resource,
            "namespace": self.namespace,
            "name": self.name,
            "code": self.code,
            "timestamp": self.timestamp,
        }
        if self.request_object is not None:
            d["requestObject"] = self.request_object
        return d


@dataclass
class PolicyRule:
    """One audit policy rule: the first rule whose user/verb/resource
    selectors match decides the level (reference ``audit/policy``)."""

    level: str = METADATA
    users: list[str] = field(default_factory=list)  # empty = any
    verbs: list[str] = field(default_factory=list)
    resources: list[str] = field(default_factory=list)

    def matches(self, user: str, verb: str, resource: str) -> bool:
        if self.users and user not in self.users:
            return False
        if self.verbs and verb not in self.verbs:
            return False
        if self.resources and resource not in self.resources:
            return False
        return True


class AuditPolicy:
    def __init__(self, rules: Optional[list[PolicyRule]] = None,
                 default_level: str = METADATA):
        self.rules = rules or []
        self.default_level = default_level

    def level_for(self, user: str, verb: str, resource: str) -> str:
        for rule in self.rules:
            if rule.matches(user, verb, resource):
                return rule.level
        return self.default_level


class Backend:
    def process(self, event: AuditEvent) -> None:
        raise NotImplementedError


class MemoryBackend(Backend):
    def __init__(self):
        self.events: list[AuditEvent] = []
        self._mu = threading.Lock()

    def process(self, event: AuditEvent) -> None:
        with self._mu:
            self.events.append(event)


class LogBackend(Backend):
    """JSON-lines audit log file (reference log backend)."""

    def __init__(self, path: str):
        self.path = path
        self._mu = threading.Lock()

    def process(self, event: AuditEvent) -> None:
        line = json.dumps(event.to_dict())
        with self._mu:
            with open(self.path, "a") as f:
                f.write(line + "\n")


class WebhookBackend(Backend):
    """POST audit events to an external collector (reference webhook
    backend, ``apiserver/plugin/pkg/audit/webhook``): batched in a
    background thread so audit never sits on the request path; a dead
    collector drops batches after ``max_buffer`` (audit must not wedge
    the apiserver)."""

    def __init__(self, url: str, batch_size: int = 100,
                 flush_interval: float = 1.0, max_buffer: int = 10_000,
                 timeout: float = 5.0):
        import queue

        self.url = url
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.timeout = timeout
        self._q: "queue.Queue[AuditEvent]" = queue.Queue(maxsize=max_buffer)
        # `dropped` is bumped from request threads (process) AND the flush
        # thread (_post); += is a lost-update race without this (RL301)
        self._drop_mu = threading.Lock()
        self.dropped = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def process(self, event: AuditEvent) -> None:
        try:
            self._q.put_nowait(event)
        except Exception:  # queue full: shed, never block the request
            with self._drop_mu:
                self.dropped += 1

    def _loop(self) -> None:
        import queue as _queue

        while not self._stop.is_set():
            batch: list[AuditEvent] = []
            try:
                batch.append(self._q.get(timeout=self.flush_interval))
            except _queue.Empty:
                continue
            while len(batch) < self.batch_size:
                try:
                    batch.append(self._q.get_nowait())
                except _queue.Empty:
                    break
            try:
                self._post(batch)
            finally:
                # task_done AFTER the POST: stop()'s drain tracks
                # unfinished_tasks, so an in-flight batch counts until it
                # is actually delivered (or given up on)
                for _ in batch:
                    self._q.task_done()

    def _post(self, batch: list[AuditEvent]) -> None:
        import urllib.request

        body = json.dumps({"kind": "EventList",
                           "items": [e.to_dict() for e in batch]}).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=self.timeout).read()
        except Exception:  # noqa: BLE001 - a dead collector loses batches
            with self._drop_mu:
                self.dropped += len(batch)

    def stop(self, drain_timeout: float = 2.0) -> None:
        import time as _t

        deadline = _t.monotonic() + drain_timeout
        # unfinished_tasks covers the batch IN FLIGHT, not just the queue:
        # a drain must not declare victory while the final POST is running
        while self._q.unfinished_tasks and _t.monotonic() < deadline:
            _t.sleep(0.05)
        self._stop.set()
        self._thread.join(timeout=2)


class Auditor:
    """Policy + backends; the apiserver calls :meth:`record` per request."""

    def __init__(self, policy: Optional[AuditPolicy] = None,
                 backends: Optional[list[Backend]] = None):
        self.policy = policy or AuditPolicy()
        self.backends = backends if backends is not None else [MemoryBackend()]

    @property
    def memory(self) -> Optional[MemoryBackend]:
        for b in self.backends:
            if isinstance(b, MemoryBackend):
                return b
        return None

    def record(self, stage: str, user: str, verb: str, resource: str,
               namespace: str, name: str, code: int = 0,
               request_object: Optional[dict] = None) -> None:
        level = self.policy.level_for(user, verb, resource)
        if level == NONE:
            return
        ev = AuditEvent(
            stage=stage, user=user, verb=verb, resource=resource,
            namespace=namespace, name=name, code=code,
            request_object=request_object if level in (REQUEST, REQUEST_RESPONSE) else None,
        )
        for b in self.backends:
            b.process(ev)
