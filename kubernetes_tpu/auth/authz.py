"""Authorization (SURVEY.md §2.9): may this user do this verb on this
resource.

Capability equivalents of the reference's authorizer modes
(``pkg/kubeapiserver/authorizer/config.go`` union of: AlwaysAllow, ABAC,
RBAC (``plugin/pkg/auth/authorizer/rbac/rbac.go``), Node
(``plugin/pkg/auth/authorizer/node``), Webhook).  Decisions follow the
reference's tri-state: allow / deny-with-no-opinion (next authorizer in the
union gets a say) — a final no-opinion is a deny.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass
from typing import Callable, Optional

from ..store.store import Store
from .authn import UserInfo

ALLOW = "allow"
DENY = "deny"
NO_OPINION = "no-opinion"


@dataclass
class AuthzAttributes:
    """Reference ``authorization/authorizer.Attributes``."""

    user: UserInfo
    verb: str  # get|list|watch|create|update|delete|bind|…
    resource: str  # plural resource name ("" for non-resource paths)
    namespace: str = ""
    name: str = ""
    path: str = ""  # non-resource path (e.g. /healthz)


class Authorizer:
    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        """Returns (decision, reason)."""
        raise NotImplementedError


class AlwaysAllow(Authorizer):
    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        return ALLOW, "always-allow"


class RBACAuthorizer(Authorizer):
    """Evaluates Role/ClusterRole bindings stored in the cluster (reference
    ``plugin/pkg/auth/authorizer/rbac/rbac.go:74 Authorize`` — visit every
    binding that names the subject, test each rule)."""

    def __init__(self, store: Store):
        self.store = store

    def _subject_matches(self, subject: dict, user: UserInfo) -> bool:
        kind = subject.get("kind", "User")
        name = subject.get("name", "")
        if kind == "User":
            return name == user.name
        if kind == "Group":
            return name in user.groups
        if kind == "ServiceAccount":
            sa_user = f"system:serviceaccount:{subject.get('namespace', '')}:{name}"
            return sa_user == user.name
        return False

    def _rules_for(self, role_kind: str, role_name: str, namespace: str) -> list[dict]:
        try:
            if role_kind == "ClusterRole":
                role = self.store.get("ClusterRole", "", role_name)
            else:
                role = self.store.get("Role", namespace, role_name)
        except KeyError:
            return []
        return role.get("rules") or []

    def _rule_allows(self, rule: dict, attrs: AuthzAttributes) -> bool:
        verbs = rule.get("verbs") or []
        resources = rule.get("resources") or []
        names = rule.get("resourceNames") or []
        if "*" not in verbs and attrs.verb not in verbs:
            return False
        if "*" not in resources and attrs.resource not in resources:
            return False
        if names and attrs.name not in names:
            return False
        return True

    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        # cluster-wide grants
        bindings, _ = self.store.list("ClusterRoleBinding", None)
        for b in bindings:
            if not any(self._subject_matches(s, attrs.user) for s in b.get("subjects") or []):
                continue
            ref = b.get("roleRef") or {}
            for rule in self._rules_for(ref.get("kind", "ClusterRole"), ref.get("name", ""), ""):
                if self._rule_allows(rule, attrs):
                    return ALLOW, f"ClusterRoleBinding {b['metadata']['name']}"
        # namespaced grants
        if attrs.namespace:
            bindings, _ = self.store.list("RoleBinding", attrs.namespace)
            for b in bindings:
                if not any(self._subject_matches(s, attrs.user) for s in b.get("subjects") or []):
                    continue
                ref = b.get("roleRef") or {}
                for rule in self._rules_for(
                    ref.get("kind", "Role"), ref.get("name", ""), attrs.namespace
                ):
                    if self._rule_allows(rule, attrs):
                        return ALLOW, f"RoleBinding {attrs.namespace}/{b['metadata']['name']}"
        return NO_OPINION, "no RBAC policy matched"


class NodeAuthorizer(Authorizer):
    """Scopes kubelet credentials to their own node's objects (reference
    ``plugin/pkg/auth/authorizer/node`` — a graph walk from node to the
    pods bound to it and the secrets/configmaps those pods reference)."""

    NODE_USER_PREFIX = "system:node:"

    def __init__(self, store: Store):
        self.store = store

    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        if not attrs.user.name.startswith(self.NODE_USER_PREFIX):
            return NO_OPINION, "not a node user"
        node_name = attrs.user.name[len(self.NODE_USER_PREFIX):]
        # Out-of-scope checks return NO_OPINION (not DENY) so a union can
        # still consult RBAC for explicit grants to node identities — the
        # reference node authorizer never hard-denies.
        if attrs.resource == "nodes":
            if attrs.name in ("", node_name):
                return ALLOW, "node accessing own Node object"
            return NO_OPINION, f"node {node_name} has no default access to node {attrs.name}"
        if attrs.resource == "pods":
            if attrs.verb in ("list", "watch"):
                return ALLOW, "node watching pod assignments"
            if attrs.name:
                try:
                    pod = self.store.get("Pod", attrs.namespace, attrs.name)
                except KeyError:
                    return NO_OPINION, "pod not found"
                if (pod.get("spec") or {}).get("nodeName") == node_name:
                    return ALLOW, "pod is bound to this node"
                return NO_OPINION, f"pod not bound to node {node_name}"
        if attrs.resource in ("secrets", "configmaps"):
            # graph edge: secret/configmap referenced by a pod on this node
            pods, _ = self.store.list("Pod", attrs.namespace)
            for pod in pods:
                if (pod.get("spec") or {}).get("nodeName") != node_name:
                    continue
                for v in (pod.get("spec") or {}).get("volumes") or []:
                    if v.get("secretName") == attrs.name or v.get("configMapName") == attrs.name:
                        return ALLOW, "referenced by pod on this node"
            return NO_OPINION, f"{attrs.resource[:-1]} not referenced by any pod on {node_name}"
        if attrs.resource in ("events",):
            return ALLOW, "nodes may emit events"
        return NO_OPINION, "resource outside node scope"


class ABACAuthorizer(Authorizer):
    """Static policy list (reference ``pkg/auth/authorizer/abac`` — one
    JSON policy object per line; ``*`` wildcards)."""

    def __init__(self, policies: list[dict]):
        self.policies = list(policies)

    @classmethod
    def from_file(cls, path: str) -> "ABACAuthorizer":
        import json

        policies = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    policies.append(json.loads(line))
        return cls(policies)

    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        for p in self.policies:
            spec = p.get("spec", p)  # tolerate both wrapped and bare policies
            user = spec.get("user", "")
            group = spec.get("group", "")
            if user and user != "*" and user != attrs.user.name:
                continue
            if group and group != "*" and group not in attrs.user.groups:
                continue
            if not fnmatch.fnmatch(attrs.resource, spec.get("resource", "*") or "*"):
                continue
            ns = spec.get("namespace", "*") or "*"
            if ns != "*" and ns != attrs.namespace:
                continue
            verb = spec.get("verb", "*") or "*"
            if verb != "*" and verb != attrs.verb:
                continue
            if spec.get("readonly") and attrs.verb not in ("get", "list", "watch"):
                continue
            return ALLOW, "ABAC policy matched"
        return NO_OPINION, "no ABAC policy matched"


class WebhookAuthorizer(Authorizer):
    """Delegates to a callable (reference ``plugin/pkg/auth/authorizer/webhook``
    posts a SubjectAccessReview; here the hook is any callable with the same
    contract)."""

    def __init__(self, hook: Callable[[AuthzAttributes], tuple[str, str]]):
        self.hook = hook

    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        return self.hook(attrs)


class UnionAuthorizer(Authorizer):
    """First allow or deny wins; no-opinion falls through (reference
    ``authorization/union``)."""

    def __init__(self, *authorizers: Authorizer):
        self.authorizers = list(authorizers)

    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        reasons = []
        for a in self.authorizers:
            decision, reason = a.authorize(attrs)
            if decision in (ALLOW, DENY):
                return decision, reason
            reasons.append(reason)
        return DENY, "; ".join(reasons) or "no authorizer had an opinion"


# privileged groups that bypass RBAC (reference bootstrap policy binds
# system:masters to cluster-admin)
MASTERS_GROUP = "system:masters"


class BootstrapPolicyAuthorizer(Authorizer):
    """system:masters → cluster-admin (reference
    ``plugin/pkg/auth/authorizer/rbac/bootstrappolicy``)."""

    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        if MASTERS_GROUP in attrs.user.groups:
            return ALLOW, "system:masters"
        return NO_OPINION, "not a master"


class AuthenticatedOrDiscovery(Authorizer):
    """The cert-mode default for a self-hosted control plane: any
    AUTHENTICATED identity (client cert, token) is allowed; anonymous
    requests are scoped to exactly the join-discovery surface — reading
    kube-public configmaps (cluster-info) and /healthz — the effective
    grant kubeadm's RBAC bootstrap gives ``system:unauthenticated``."""

    def authorize(self, attrs: AuthzAttributes) -> tuple[str, str]:
        if attrs.user.authenticated:
            return ALLOW, "authenticated"
        if (attrs.verb in ("get", "list")
                and attrs.resource == "configmaps"
                and attrs.namespace == "kube-public"):
            return ALLOW, "anonymous discovery (cluster-info)"
        if attrs.verb == "get" and attrs.path in ("/healthz", "/version"):
            return ALLOW, "anonymous health"
        return DENY, "anonymous access is limited to join discovery"
