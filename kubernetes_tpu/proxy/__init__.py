"""Service dataplane (SURVEY.md L7: kube-proxy, ``pkg/proxy``)."""

from .proxier import EndpointInfo, Proxier, Rule, ServicePortName
from .hollow import HollowProxy, HollowProxyFleet
from .healthcheck import ProxierHealthServer, ServiceHealthServer
from .userspace import UserspaceProxier
