"""Proxy health checking (reference ``pkg/proxy/healthcheck/``): two
distinct surfaces —

- :class:`ProxierHealthServer` — the NODE's proxier healthz
  (``healthcheck.go healthzServer``): 200 while rule syncs are recent,
  503 once the proxier stalls past the grace period.  Load balancers use
  this to stop sending new flows to a node whose dataplane is stale.
- :class:`ServiceHealthServer` — per-service endpoint counts for
  externalTrafficPolicy=Local services (``healthcheck.go server``): an LB
  health-probes a node's per-service port and only targets nodes with
  LOCAL ready endpoints.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class _HealthHTTPServer:
    """Shared server lifecycle; subclasses implement
    ``handle(path) -> (code, body_dict) | None`` (None = 404)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                result = outer.handle(self.path)
                if result is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                code, payload = result
                if isinstance(payload, str):
                    # raw text responses (Prometheus exposition format)
                    body = payload.encode()
                    ctype = "text/plain"
                else:
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def handle(self, path: str):  # pragma: no cover - abstract
        raise NotImplementedError

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()  # release the bound socket either way


class ProxierHealthServer(_HealthHTTPServer):
    def __init__(self, grace_seconds: float = 60.0, clock=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.grace = grace_seconds
        self.clock = clock or time.monotonic
        self._last_sync = self.clock()
        self._lock = threading.Lock()
        super().__init__(host, port)

    def touch(self) -> None:
        """Called by the proxier after every successful rule sync."""
        with self._lock:
            self._last_sync = self.clock()

    def status(self) -> tuple[bool, float]:
        with self._lock:
            age = self.clock() - self._last_sync
        return age <= self.grace, age

    def handle(self, path: str):
        if path != "/healthz":
            return None
        healthy, age = self.status()
        return (200 if healthy else 503,
                {"lastUpdated": round(age, 3), "healthy": healthy})


class ServiceHealthServer(_HealthHTTPServer):
    """Per-service local-endpoint counts, one shared HTTP server (the
    reference binds one port per service; a path per service keys the
    same contract without exhausting test ports)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()
        super().__init__(host, port)

    def sync_services(self, counts: dict[str, int]) -> None:
        """Full-state update of tracked services (``SyncServices`` +
        ``SyncEndpoints``): services absent from ``counts`` stop being
        served (404)."""
        with self._lock:
            self._counts = dict(counts)

    def handle(self, path: str):
        key = path.strip("/")
        with self._lock:
            count = self._counts.get(key)
        if count is None:
            return None
        # 0 local endpoints -> 503: the LB must not target this node for a
        # Local-policy service it has no backends on
        return (200 if count > 0 else 503,
                {"service": key, "localEndpoints": count})
