"""Userspace proxy mode: a real per-connection TCP forwarder.

Capability of the reference's legacy userspace proxier
(``pkg/proxy/userspace/proxier.go`` + ``roundrobin.go`` LoadBalancerRR,
2,088 LoC): one listening socket per service port; each accepted
connection picks a backend via round-robin (or the caller's sticky
affinity entry) and bytes are pumped both ways until either side closes.
Where the iptables mode synthesizes NAT rules (``proxier.py``), this mode
actually terminates and re-dials connections — the trade the reference
retired it over (two copies through userspace per byte), kept here both
for mode parity and because it is the one proxier a test can point real
sockets at.
"""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class _Backend:
    host: str
    port: int


@dataclass
class _ServiceState:
    listener: socket.socket
    proxy_port: int
    backends: list[_Backend] = field(default_factory=list)
    rr_index: int = 0
    affinity: str = "None"
    # client ip -> backend index (ClientIP affinity, roundrobin.go
    # affinityState)
    sticky: dict[str, int] = field(default_factory=dict)
    conns: int = 0


class UserspaceProxier:
    """Listens on ephemeral localhost ports, one per service key, and
    forwards accepted connections to the service's backends."""

    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self._services: dict[str, _ServiceState] = {}
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._stopped = threading.Event()

    # -- service table (OnServiceUpdate / OnEndpointsUpdate) ---------------
    def set_service(self, key: str, backends: list[tuple[str, int]],
                    affinity: str = "None", local_port: int = 0) -> int:
        """Create/update a proxied service; returns the local proxy port
        (the reference allocates a node port per userspace service).
        ``local_port`` pins the listener (port-forward's LOCAL:REMOTE);
        0 = ephemeral."""
        with self._lock:
            st = self._services.get(key)
            if st is None:
                listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                listener.bind((self.host, local_port))
                listener.listen(64)
                st = _ServiceState(listener=listener,
                                   proxy_port=listener.getsockname()[1])
                self._services[key] = st
                t = threading.Thread(target=self._accept_loop, args=(key, st),
                                     daemon=True)
                t.start()
                self._threads.append(t)
            old = {(b.host, b.port) for b in st.backends}
            st.backends = [_Backend(h, p) for h, p in backends]
            st.affinity = affinity
            new = {(b.host, b.port) for b in st.backends}
            if old != new:
                # endpoints changed: sticky entries pointing at removed
                # backends are stale (proxier.go deleteEndpointConnections)
                st.sticky.clear()
                st.rr_index = 0
            return st.proxy_port

    def remove_service(self, key: str) -> None:
        with self._lock:
            st = self._services.pop(key, None)
        if st is not None:
            try:
                st.listener.close()
            except OSError:
                pass

    def proxy_port(self, key: str) -> Optional[int]:
        with self._lock:
            st = self._services.get(key)
            return st.proxy_port if st else None

    def stats(self, key: str) -> dict:
        with self._lock:
            st = self._services.get(key)
            if st is None:
                return {}
            return {"conns": st.conns, "backends": len(st.backends)}

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            for st in self._services.values():
                try:
                    st.listener.close()
                except OSError:
                    pass
            self._services.clear()

    # -- data path ----------------------------------------------------------
    def _pick(self, st: _ServiceState, client_ip: str) -> Optional[_Backend]:
        """LoadBalancerRR.NextEndpoint: sticky hit first, else advance the
        round-robin cursor (and record it when affinity is on)."""
        if not st.backends:
            return None
        if st.affinity == "ClientIP":
            idx = st.sticky.get(client_ip)
            if idx is not None and idx < len(st.backends):
                return st.backends[idx]
        idx = st.rr_index % len(st.backends)
        st.rr_index += 1
        if st.affinity == "ClientIP":
            st.sticky[client_ip] = idx
        return st.backends[idx]

    def _accept_loop(self, key: str, st: _ServiceState) -> None:
        while not self._stopped.is_set():
            try:
                conn, addr = st.listener.accept()
            except OSError:
                return  # listener closed (service removed / stop)
            with self._lock:
                if self._services.get(key) is not st:
                    conn.close()
                    return
                backend = self._pick(st, addr[0])
                st.conns += 1
            if backend is None:
                conn.close()  # no endpoints: REJECT analogue
                continue
            threading.Thread(target=self._proxy_conn,
                             args=(conn, backend), daemon=True).start()

    def _proxy_conn(self, client: socket.socket, backend: _Backend) -> None:
        try:
            upstream = socket.create_connection((backend.host, backend.port),
                                                timeout=5)
        except OSError:
            client.close()
            return

        done = {"count": 0}
        done_lock = threading.Lock()

        def pump(src, dst):
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                # propagate EOF as a half-close only: a client that shuts
                # its write side (FIN-delimited request) must still be able
                # to READ the backend's reply through the other pump
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                with done_lock:
                    done["count"] += 1
                    finished = done["count"] == 2
                if finished:
                    for s in (src, dst):
                        try:
                            s.close()
                        except OSError:
                            pass

        threading.Thread(target=pump, args=(client, upstream), daemon=True).start()
        threading.Thread(target=pump, args=(upstream, client), daemon=True).start()
