"""Per-node service VIP dataplane: full-state rule sync + routing.

Capability of the reference's iptables proxier
(``pkg/proxy/iptables/proxier.go``, 1,752 LoC):

- ``syncProxyRules`` (``proxier.go:966``) is a FULL-STATE rewrite: every
  sync rebuilds the complete NAT table from the current Services and
  Endpoints maps — no incremental rule surgery.  ``Proxier.sync()`` does
  the same: it derives a fresh ``RuleTable`` (the iptables-restore
  analogue) from the accumulated change trackers.
- Change trackers (``serviceChanges`` / ``endpointsChanges``,
  ``proxier.go:203,260``): informer events record deltas; the sync loop
  folds them into ``service_map`` / ``endpoints_map`` and marks the
  table dirty.
- Per-rule semantics mirrored: ClusterIP → DNAT to a ready endpoint,
  NodePort rules, REJECT for VIPs with no endpoints, session affinity
  (ClientIP mode with timeout, ``proxier.go:169 affinityState``),
  headless services (no clusterIP) produce no rules, only READY
  addresses are load-balancing targets.
- Stale-affinity cleanup on endpoint removal (``proxier.go:1120``
  ``deleteEndpointConnections`` analogue — we drop sticky entries whose
  endpoint vanished).

The routing itself (``route()``) models the kernel's packet path so the
fleet and e2e tests can send "traffic" through the table; selection is
round-robin per service port (the userspace proxier's ``LoadBalancerRR``,
``pkg/proxy/userspace/roundrobin.go``) — the iptables mode's random
statistic match has the same distributional contract.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api import types as api
from ..api.cluster import Endpoints

DEFAULT_AFFINITY_TIMEOUT = 10800.0  # seconds (reference v1.7 default)


@dataclass(frozen=True)
class ServicePortName:
    """One load-balanced unit: a (service, port-name) pair
    (``pkg/proxy/types.go`` ServicePortName)."""

    namespace: str
    name: str
    port: str  # ServicePort.name ("" for single unnamed port)

    def __str__(self) -> str:
        return f"{self.namespace}/{self.name}:{self.port}"


@dataclass(frozen=True)
class EndpointInfo:
    ip: str
    port: int
    is_local: bool  # backing pod runs on this proxier's node


@dataclass
class ServiceInfo:
    cluster_ip: str
    port: int
    protocol: str
    node_port: int
    session_affinity: str  # "None" | "ClientIP"
    affinity_timeout: float = DEFAULT_AFFINITY_TIMEOUT


@dataclass
class Rule:
    """One synthesized dataplane rule (an iptables chain analogue)."""

    kind: str  # "cluster" | "nodeport" | "reject"
    vip: str
    port: int
    protocol: str
    service: ServicePortName
    endpoints: tuple[EndpointInfo, ...] = ()


class _AffinityState:
    __slots__ = ("endpoint", "last_used")

    def __init__(self, endpoint: EndpointInfo, now: float):
        self.endpoint = endpoint
        self.last_used = now


class Proxier:
    """One node's dataplane.  Feed it service/endpoints deltas (informer
    handlers), call ``sync()``, then ``route()`` traffic through it."""

    def __init__(self, node_name: str = "", clock: Callable[[], float] = time.monotonic):
        self.node_name = node_name
        self.clock = clock
        self._lock = threading.Lock()
        # accumulated change-tracker state (folded on sync)
        self._pending_services: dict[str, Optional[api.Service]] = {}
        self._pending_endpoints: dict[str, Optional[Endpoints]] = {}
        # folded maps
        self.service_map: dict[ServicePortName, ServiceInfo] = {}
        self.endpoints_map: dict[ServicePortName, tuple[EndpointInfo, ...]] = {}
        self._services_by_key: dict[str, api.Service] = {}
        self._endpoints_by_key: dict[str, Endpoints] = {}
        # derived rule table + runtime LB state
        self.rules: dict[tuple, Rule] = {}
        self._rr: dict[ServicePortName, int] = {}
        self._affinity: dict[tuple[ServicePortName, str], _AffinityState] = {}
        self.syncs = 0
        self.last_sync: float = 0.0

    # -- change trackers (informer side) -----------------------------------
    def on_service_update(self, svc: Optional[api.Service], key: Optional[str] = None) -> None:
        """svc=None (with key) records a deletion."""
        with self._lock:
            if svc is None:
                if key:
                    self._pending_services[key] = None
            else:
                self._pending_services[svc.meta.key] = svc

    def on_endpoints_update(self, eps: Optional[Endpoints], key: Optional[str] = None) -> None:
        with self._lock:
            if eps is None:
                if key:
                    self._pending_endpoints[key] = None
            else:
                self._pending_endpoints[eps.meta.key] = eps

    # -- full-state sync (syncProxyRules) ----------------------------------
    def _fold_changes(self) -> None:
        for key, svc in self._pending_services.items():
            if svc is None:
                self._services_by_key.pop(key, None)
            else:
                self._services_by_key[key] = svc
        for key, eps in self._pending_endpoints.items():
            if eps is None:
                self._endpoints_by_key.pop(key, None)
            else:
                self._endpoints_by_key[key] = eps
        self._pending_services.clear()
        self._pending_endpoints.clear()

    def _build_service_map(self) -> dict[ServicePortName, ServiceInfo]:
        out: dict[ServicePortName, ServiceInfo] = {}
        for svc in self._services_by_key.values():
            # headless services get no VIP rules (proxier.go shouldSkipService)
            if svc.cluster_ip in ("", "None"):
                continue
            for sp in svc.ports:
                spn = ServicePortName(svc.meta.namespace, svc.meta.name, sp.name)
                out[spn] = ServiceInfo(
                    cluster_ip=svc.cluster_ip,
                    port=sp.port,
                    protocol=sp.protocol,
                    node_port=sp.node_port if svc.type in ("NodePort", "LoadBalancer") else 0,
                    session_affinity=svc.session_affinity,
                )
        return out

    def _build_endpoints_map(self) -> dict[ServicePortName, tuple[EndpointInfo, ...]]:
        out: dict[ServicePortName, tuple[EndpointInfo, ...]] = {}
        for eps in self._endpoints_by_key.values():
            ns, name = eps.meta.namespace, eps.meta.name
            for subset in eps.subsets:
                for ep_port in subset.ports:
                    spn = ServicePortName(ns, name, ep_port.name)
                    infos = tuple(
                        EndpointInfo(
                            ip=a.ip,
                            port=ep_port.port,
                            is_local=bool(self.node_name) and a.node_name == self.node_name,
                        )
                        # only READY addresses load-balance (notReady excluded)
                        for a in subset.addresses
                    )
                    out[spn] = out.get(spn, ()) + infos
        return out

    # optional ProxierHealthServer (healthcheck.py): touched after every
    # successful sync so the node healthz reflects dataplane freshness
    health_server = None

    def sync(self) -> dict[tuple, Rule]:
        """Rebuild the whole rule table (one iptables-restore batch).
        A no-delta resync is a heartbeat: it refreshes health/affinity
        bookkeeping without rebuilding identical maps."""
        with self._lock:
            if self.syncs > 0 and not self._pending_services and not self._pending_endpoints:
                self._expire_affinity()
                self.syncs += 1
                self.last_sync = self.clock()
                if self.health_server is not None:
                    self.health_server.touch()
                return self.rules
            self._fold_changes()
            self.service_map = self._build_service_map()
            self.endpoints_map = self._build_endpoints_map()

            rules: dict[tuple, Rule] = {}
            for spn, info in self.service_map.items():
                eps = self.endpoints_map.get(spn, ())
                if not eps:
                    # VIP with no backends REJECTs (proxier.go:1396)
                    rules[("reject", info.cluster_ip, info.port, info.protocol)] = Rule(
                        kind="reject", vip=info.cluster_ip, port=info.port,
                        protocol=info.protocol, service=spn,
                    )
                    continue
                rules[("cluster", info.cluster_ip, info.port, info.protocol)] = Rule(
                    kind="cluster", vip=info.cluster_ip, port=info.port,
                    protocol=info.protocol, service=spn, endpoints=eps,
                )
                if info.node_port:
                    rules[("nodeport", "", info.node_port, info.protocol)] = Rule(
                        kind="nodeport", vip="", port=info.node_port,
                        protocol=info.protocol, service=spn, endpoints=eps,
                    )
            self.rules = rules

            # drop sticky entries whose endpoint vanished
            live: set[tuple[ServicePortName, EndpointInfo]] = {
                (spn, ep) for spn, eps in self.endpoints_map.items() for ep in eps
            }
            self._affinity = {
                k: st for k, st in self._affinity.items() if (k[0], st.endpoint) in live
            }
            self._expire_affinity()
            self.syncs += 1
            self.last_sync = self.clock()
            if self.health_server is not None:
                self.health_server.touch()
            return rules

    def _expire_affinity(self) -> None:
        """Prune sticky entries past their service's timeout — one-time
        client IPs must not accumulate forever (lock held by caller)."""
        now = self.clock()
        stale = [
            k for k, st in self._affinity.items()
            if now - st.last_used > self.service_map.get(
                k[0], ServiceInfo("", 0, "", 0, "None")
            ).affinity_timeout
        ]
        for k in stale:
            del self._affinity[k]

    # -- the packet path ----------------------------------------------------
    def _pick(self, spn: ServicePortName, eps: tuple[EndpointInfo, ...],
              info: ServiceInfo, client_ip: str) -> EndpointInfo:
        now = self.clock()
        if info.session_affinity == "ClientIP" and client_ip:
            akey = (spn, client_ip)
            st = self._affinity.get(akey)
            if st is not None and now - st.last_used <= info.affinity_timeout:
                st.last_used = now
                return st.endpoint
        i = self._rr.get(spn, 0)
        ep = eps[i % len(eps)]
        self._rr[spn] = i + 1
        if info.session_affinity == "ClientIP" and client_ip:
            self._affinity[(spn, client_ip)] = _AffinityState(ep, now)
        return ep

    def route(self, vip: str, port: int, protocol: str = "TCP",
              client_ip: str = "") -> Optional[EndpointInfo]:
        """ClusterIP path: returns the chosen backend, or None (REJECT)."""
        with self._lock:
            rule = self.rules.get(("cluster", vip, port, protocol))
            if rule is None or not rule.endpoints:
                return None
            info = self.service_map[rule.service]
            return self._pick(rule.service, rule.endpoints, info, client_ip)

    def route_node_port(self, node_port: int, protocol: str = "TCP",
                        client_ip: str = "") -> Optional[EndpointInfo]:
        with self._lock:
            rule = self.rules.get(("nodeport", "", node_port, protocol))
            if rule is None or not rule.endpoints:
                return None
            info = self.service_map[rule.service]
            return self._pick(rule.service, rule.endpoints, info, client_ip)

    # -- health (pkg/proxy/healthcheck) ------------------------------------
    def local_endpoint_count(self, namespace: str, name: str) -> int:
        """Ready endpoints on this node, per service — what the reference's
        service health-check server reports for LB traffic policies."""
        with self._lock:
            total = 0
            seen: set[str] = set()
            for spn, eps in self.endpoints_map.items():
                if spn.namespace != namespace or spn.name != name:
                    continue
                for ep in eps:
                    if ep.is_local and ep.ip not in seen:
                        seen.add(ep.ip)
                        total += 1
            return total

    def healthz(self, stale_after: float = 60.0) -> bool:
        return self.syncs > 0 and (self.clock() - self.last_sync) <= stale_after
