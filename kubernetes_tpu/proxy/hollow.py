"""HollowProxy: the per-node proxy agent wired to informers.

Capability of kubemark's HollowProxy (``pkg/kubemark/hollow_proxy.go``):
a real Proxier fed by Service/Endpoints watches, with no kernel
underneath.  A fleet of these alongside ``HollowFleet`` models the full
node dataplane at 5k-node scale on one machine.

Scale shape: one shared Service informer + one shared Endpoints informer
drive EVERY hollow proxier's change trackers (the informer fan-out of
SURVEY.md P4); each node's ``sync()`` then folds only its own pending
deltas."""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..client.clientset import Clientset
from ..client.informer import Handler, InformerFactory
from .proxier import Proxier


class HollowProxy:
    def __init__(
        self,
        clientset: Clientset,
        node_name: str,
        informers: Optional[InformerFactory] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.clientset = clientset
        self.proxier = Proxier(node_name=node_name, clock=clock)
        self.informers = informers or InformerFactory(clientset)
        self._wire()

    def _wire(self) -> None:
        p = self.proxier
        self.informers.informer("Service").add_handler(Handler(
            on_add=lambda s: p.on_service_update(s),
            on_update=lambda old, new: p.on_service_update(new),
            on_delete=lambda s: p.on_service_update(None, key=s.meta.key),
        ))
        self.informers.informer("Endpoints").add_handler(Handler(
            on_add=lambda e: p.on_endpoints_update(e),
            on_update=lambda old, new: p.on_endpoints_update(new),
            on_delete=lambda e: p.on_endpoints_update(None, key=e.meta.key),
        ))

    def start(self) -> None:
        self.informers.start_all_manual()
        self.proxier.sync()

    def tick(self) -> None:
        """Pump watches and resync the table (the proxier's syncPeriod)."""
        self.informers.pump_all()
        self.proxier.sync()


class HollowProxyFleet:
    """N hollow proxies sharing one informer factory."""

    def __init__(
        self,
        clientset: Clientset,
        node_names: list[str],
        clock: Callable[[], float] = time.monotonic,
    ):
        self.informers = InformerFactory(clientset)
        self.proxies = [
            HollowProxy(clientset, name, informers=self.informers, clock=clock)
            for name in node_names
        ]

    def start(self) -> None:
        self.informers.start_all_manual()
        for p in self.proxies:
            p.proxier.sync()

    def tick_all(self) -> None:
        self.informers.pump_all()
        for p in self.proxies:
            p.proxier.sync()
