"""Client machinery: clientset, informers, workqueues (SURVEY.md L5)."""

from .clientset import BindConflictError, Clientset, PodClient, TypedClient
from .informer import CacheMutationError, Handler, InformerFactory, PodNodeIndex, SharedInformer
from .workqueue import ExponentialBackoff, WorkQueue
