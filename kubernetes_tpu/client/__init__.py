"""Client machinery: clientset, informers, workqueues (SURVEY.md L5)."""

from .clientset import BindConflictError, Clientset, PodClient, TypedClient
from .informer import CacheMutationError, Handler, InformerFactory, PodNodeIndex, PodOwnerIndex, SharedInformer
from .workqueue import ExponentialBackoff, WorkQueue
from .leaderelection import LeaderElector
from .record import EventBroadcaster, EventCorrelator, EventRecorder
