"""Event recording: correlate, aggregate, rate-limit, write asynchronously.

Capability of the reference's ``client-go/tools/record`` stack:

- ``EventBroadcaster`` — emitters never block on the API: events enter a
  bounded in-memory queue consumed by a background writer thread
  (reference: the watch channel + ``StartRecordingToSink``).  When the
  queue is full the newest event is dropped and counted (the reference
  drops on sink backpressure via its rate limiter).
- ``EventCorrelator`` (``tools/record/events_cache.go``) —
  - *aggregation*: more than ``max_similar`` events in the same group
    (source + object + type + reason) inside ``similar_window`` collapse
    into ONE "(combined from similar events)" event whose count rises;
  - *dedup*: an identical event (same message too) bumps ``count`` on the
    stored object via CAS instead of minting a new one;
  - *spam filter*: a token bucket per source+object (``burst`` tokens,
    one refill per ``refill_period``) drops floods outright.

The TPU-native consequence: the scheduler's hot batch loop only appends
to a deque; all store writes happen off the timed path, exactly like the
reference's async goroutine sink.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..api import types as api

logger = logging.getLogger("kubernetes_tpu.record")


@dataclass
class _PendingEvent:
    involved_kind: str
    involved_key: str  # namespace/name (or bare name for cluster-scoped)
    namespace: str
    etype: str
    reason: str
    # str, or a lazy ("fmt %s", arg, ...) tuple formatted on the SINK
    # thread — the emitting hot loop never pays string interpolation
    message: object
    time: float = 0.0  # emitter-side clock; correlation uses THIS, not
    # drain time, so a backed-up sink doesn't warp windows/buckets


def _fmt(message) -> str:
    return message if isinstance(message, str) else message[0] % tuple(message[1:])


@dataclass
class _PendingBatch:
    """A whole wave's events, unexpanded: the emitting scheduler thread
    enqueues (objects, kind, timestamp) and the SINK thread builds the
    per-event records — at 2k bindings per wave the dataclass
    construction alone is measurable on the timed path, and the sink
    drains concurrently with the next wave's device execution anyway."""

    items: list  # [(obj, etype, reason, message), ...]
    kind: str
    time: float

    def expand(self) -> list[_PendingEvent]:
        kind = self.kind
        now = self.time
        return [
            _PendingEvent(
                involved_kind=getattr(obj, "KIND", kind),
                involved_key=obj.meta.key,
                namespace=obj.meta.namespace,
                etype=etype,
                reason=reason,
                message=message,
                time=now,
            )
            for obj, etype, reason, message in self.items
        ]


def _expand_chunk(chunk: list) -> list[_PendingEvent]:
    out: list[_PendingEvent] = []
    for ev in chunk:
        if isinstance(ev, _PendingBatch):
            out.extend(ev.expand())
        else:
            out.append(ev)
    return out


class _TokenBucket:
    __slots__ = ("tokens", "last")

    def __init__(self, burst: int, now: float):
        self.tokens = float(burst)
        self.last = now

    def take(self, burst: int, refill_period: float, now: float) -> bool:
        if refill_period > 0:
            self.tokens = min(
                float(burst), self.tokens + (now - self.last) / refill_period
            )
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class EventCorrelator:
    """Pure decision logic, shared by sync and async paths.

    ``observe`` returns one of:
    - ``("create", event_dict)`` — mint a new Event object;
    - ``("patch", stored_name, namespace)`` — bump count on a prior event;
    - ``("drop", None, None)`` — spam-filtered.
    """

    def __init__(
        self,
        source: str = "",
        clock: Callable[[], float] = time.monotonic,
        max_similar: int = 10,
        similar_window: float = 600.0,
        burst: int = 25,
        refill_period: float = 300.0 / 25.0,
        cache_size: int = 4096,
    ):
        self.source = source
        self.clock = clock
        self.max_similar = max_similar
        self.similar_window = similar_window
        self.burst = burst
        self.refill_period = refill_period
        self._lock = threading.Lock()
        # spam filter state per source+object (LRU: hits refresh recency)
        self._buckets: collections.OrderedDict[str, _TokenBucket] = collections.OrderedDict()
        # aggregation state per similarity group: [count, window_start]
        self._similar: collections.OrderedDict[tuple, list] = collections.OrderedDict()
        # dedup cache: full event identity -> stored event name
        self._seen: collections.OrderedDict[tuple, str] = collections.OrderedDict()
        self._cache_size = cache_size
        self._name_seq = 0
        self.stats = {"created": 0, "patched": 0, "dropped_spam": 0, "aggregated": 0}

    def _trim(self, od: collections.OrderedDict) -> None:
        while len(od) > self._cache_size:
            od.popitem(last=False)

    def observe(self, ev: _PendingEvent):
        with self._lock:
            return self._observe_locked(ev)

    def observe_many(self, evs: list[_PendingEvent]) -> list:
        """Correlate a whole drained chunk under ONE lock acquisition."""
        with self._lock:
            return [self._observe_locked(ev) for ev in evs]

    def _observe_locked(self, ev: _PendingEvent):
        now = ev.time
        # -- spam filter (EventSourceObjectSpamFilter) ------------------
        bkey = f"{self.source}\x00{ev.involved_key}"
        bucket = self._buckets.get(bkey)
        if bucket is None:
            bucket = self._buckets[bkey] = _TokenBucket(self.burst, now)
            self._trim(self._buckets)
        else:
            self._buckets.move_to_end(bkey)
        if not bucket.take(self.burst, self.refill_period, now):
            self.stats["dropped_spam"] += 1
            return ("drop", None, None)

        # -- aggregation by similarity group ----------------------------
        group = (ev.involved_kind, ev.involved_key, ev.etype, ev.reason)
        rec = self._similar.get(group)
        if rec is None or now - rec[1] > self.similar_window:
            rec = self._similar[group] = [0, now]
            self._trim(self._similar)
        else:
            self._similar.move_to_end(group)
        rec[0] += 1
        aggregated = rec[0] > self.max_similar
        if aggregated:
            self.stats["aggregated"] += 1

        # -- dedup (bump count on an identical prior event) -------------
        # (key on the FORMATTED message so a str emit and a lazy-tuple
        # emit of the same final text dedup together; formatting happens
        # here on the sink thread, never on the emitting hot path)
        message = _fmt(ev.message)
        ident = group if aggregated else group + (message,)
        stored = self._seen.get(ident)
        if stored is not None:
            self._seen.move_to_end(ident)
            self.stats["patched"] += 1
            return ("patch", stored, ev.namespace)
        if aggregated:
            message = f"(combined from similar events): {message}"

        self._name_seq += 1
        _, name = (ev.involved_key.rsplit("/", 1) + [ev.involved_key])[:2] \
            if "/" in ev.involved_key else ("", ev.involved_key)
        stored_name = f"{name}.{self._name_seq:x}"
        self._seen[ident] = stored_name
        self._trim(self._seen)
        self.stats["created"] += 1
        return (
            "create",
            api.Event(
                meta=api.ObjectMeta(name=stored_name, namespace=ev.namespace),
                involved_kind=ev.involved_kind,
                involved_key=ev.involved_key,
                reason=ev.reason,
                message=message,
                type=ev.etype,
                count=1,
            ),
            ev.namespace,
        )


class EventBroadcaster:
    """Bounded queue + background writer (StartRecordingToSink)."""

    def __init__(
        self,
        clientset,
        source: str = "",
        clock: Callable[[], float] = time.monotonic,
        max_queued: int = 1_000_000,
        correlator: Optional[EventCorrelator] = None,
    ):
        self.clientset = clientset
        self.correlator = correlator or EventCorrelator(source=source, clock=clock)
        # entries are _PendingEvent or _PendingBatch; the bound and all
        # accounting (overflow drops, __len__) are in EVENTS — a batch
        # weighs len(items), so the documented memory bound holds no
        # matter how waves are packaged
        self._queue: collections.deque = collections.deque()
        self._queued_events = 0
        self._max_queued = max_queued
        self._cv = threading.Condition()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self.dropped_overflow = 0

    # -- emitter side (hot path: append only) ------------------------------
    @staticmethod
    def _weight(ev) -> int:
        return len(ev.items) if isinstance(ev, _PendingBatch) else 1

    def enqueue(self, ev: _PendingEvent) -> None:
        with self._cv:
            if self._queued_events >= self._max_queued:
                self.dropped_overflow += 1
                return
            self._queue.append(ev)
            self._queued_events += 1
            self._cv.notify()

    def enqueue_many(self, evs: list) -> None:
        """Batch append under ONE lock acquisition + ONE sink wake-up — the
        batch scheduler's whole bind wave enqueues without per-event
        synchronization (and without waking the sink mid-timed-section).
        Entries may be unexpanded _PendingBatch items; room and drops are
        accounted in EVENTS (a batch truncates to the remaining room)."""
        with self._cv:
            appended = False
            for ev in evs:
                w = self._weight(ev)
                room = self._max_queued - self._queued_events
                if room <= 0:
                    self.dropped_overflow += w
                    continue
                if w > room:
                    self.dropped_overflow += w - room
                    if isinstance(ev, _PendingBatch):
                        ev = _PendingBatch(items=ev.items[:room],
                                           kind=ev.kind, time=ev.time)
                        w = room
                    else:
                        continue
                self._queue.append(ev)
                self._queued_events += w
                appended = True
            if appended:
                self._cv.notify()

    def recorder(self, involved_kind: str = "Pod") -> "EventRecorder":
        return EventRecorder(self, involved_kind)

    # -- sink side ---------------------------------------------------------
    def _write(self, decision) -> None:
        action, payload, namespace = decision
        try:
            if action == "create":
                # no return decode: the sink never reads the stored copy
                self.clientset.events.create_nowait(payload)
            elif action == "create_many":
                # a whole chunk's creates as ONE store txn (the batched
                # event-creation satellite); clients without the batch
                # verb degrade to the per-item loop
                batch_fn = getattr(self.clientset.events,
                                   "create_many_nowait", None)
                if batch_fn is not None:
                    batch_fn(payload)
                else:
                    for ev in payload:
                        self.clientset.events.create_nowait(ev)
            elif action == "patch":
                def _bump(cur: api.Event) -> api.Event:
                    cur.count += 1
                    return cur

                self.clientset.events.guaranteed_update(payload, _bump, namespace)
        except Exception:  # events are best-effort, like the reference sink
            logger.debug("event write failed", exc_info=True)

    def _write_chunk(self, decisions) -> None:
        """Write one correlated chunk: every "create" decision is folded
        into ONE ``("create_many", [events], None)`` decision — a single
        batch store txn (one lock/WAL/fanout pass) instead of a per-Event
        commit.  "patch" decisions (count bumps on prior events) stay
        per-item CAS loops.  Create order within the chunk is preserved
        (patches target already-stored names, so their relative order to
        creates is immaterial).  Everything still flows through
        ``_write`` — the single best-effort/override seam."""
        creates = [payload for action, payload, _ns in decisions
                   if action == "create"]
        if creates:
            self._write(("create_many", creates, None))
        for decision in decisions:
            if decision[0] != "create":
                self._write(decision)

    def process_one(self) -> bool:
        """Synchronous drain step (tests / manual pumping)."""
        with self._cv:
            if not self._queue:
                return False
            ev = self._queue.popleft()
            self._queued_events -= self._weight(ev)
        for pe in _expand_chunk([ev]):
            self._write(self.correlator.observe(pe))
        return True

    def process_batch(self, max_n: int = 4096) -> int:
        """Pop a chunk, correlate it under one lock, write the decisions."""
        with self._cv:
            if not self._queue:
                return 0
            chunk = [self._queue.popleft()
                     for _ in range(min(max_n, len(self._queue)))]
            self._queued_events -= sum(self._weight(ev) for ev in chunk)
        chunk = _expand_chunk(chunk)
        self._write_chunk(self.correlator.observe_many(chunk))
        return len(chunk)

    def flush(self) -> int:
        n = 0
        while (k := self.process_batch()):
            n += k
        return n

    def start(self) -> None:
        if self._thread is not None:
            if self._thread.is_alive():
                return
            self._thread = None  # stale handle from a timed-out stop()
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait(timeout=0.2)
                if self._stopped and not self._queue:
                    return
                chunk = [self._queue.popleft()
                         for _ in range(min(4096, len(self._queue)))]
                self._queued_events -= sum(self._weight(ev) for ev in chunk)
            if chunk:
                self._write_chunk(
                    self.correlator.observe_many(_expand_chunk(chunk)))

    @property
    def running(self) -> bool:
        # a dead thread (e.g. it finished draining after a timed-out
        # stop()) is not a running sink — misreporting True here would
        # suppress callers' manual-drain fallbacks
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        with self._cv:
            self._stopped = True
            if not drain:
                self._queue.clear()
                self._queued_events = 0
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            # a huge backlog can outlive one join timeout; keep waiting for
            # THE THREAD (a concurrent caller-side flush would invert
            # create/patch ordering) — but only up to `timeout`: a sink
            # wedged inside _write must not hang stop() forever
            deadline = time.monotonic() + timeout
            while t.is_alive() and time.monotonic() < deadline:
                t.join(timeout=min(10.0, max(0.1, deadline - time.monotonic())))
                if not drain:
                    break
            if t.is_alive():
                logger.warning(
                    "event sink still draining after %.0fs; leaving the "
                    "thread to finish (%d events queued)", timeout,
                    self._queued_events)
                return  # keep _thread set so start() cannot double-sink
            self._thread = None
        if drain and (t is None or not t.is_alive()):
            self.flush()  # manual mode, or a remainder after thread exit

    def __len__(self) -> int:
        return self._queued_events  # pending EVENTS (batches pre-counted)


class EventRecorder:
    """The per-component emitting facade (reference ``EventRecorder``)."""

    def __init__(self, broadcaster: EventBroadcaster, involved_kind: str = "Pod"):
        self.broadcaster = broadcaster
        self.involved_kind = involved_kind

    def event(self, obj, etype: str, reason: str, message) -> None:
        meta = getattr(obj, "meta", None)
        key = meta.key if meta is not None else str(obj)
        namespace = meta.namespace if meta is not None else "default"
        self.broadcaster.enqueue(
            _PendingEvent(
                involved_kind=getattr(obj, "KIND", self.involved_kind),
                involved_key=key,
                namespace=namespace,
                etype=etype,
                reason=reason,
                message=message,
                time=self.broadcaster.correlator.clock(),
            )
        )

    def event_batch(self, items) -> None:
        """items: iterable of (obj, etype, reason, message) — message may be
        a lazy ("fmt %s", arg) tuple.  One timestamp, one lock, one wake —
        and ZERO per-event construction on the emitting thread: the batch
        rides the queue unexpanded (_PendingBatch) and the sink builds the
        records while the next wave owns the hot path."""
        items = list(items)
        if not items:
            return
        self.broadcaster.enqueue_many([_PendingBatch(
            items=items,
            kind=self.involved_kind,
            time=self.broadcaster.correlator.clock(),
        )])
