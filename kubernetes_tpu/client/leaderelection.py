"""Leader election: active/passive HA via a store-held lease.

Capability of ``client-go/tools/leaderelection``
(``leaderelection.go:152 RunOrDie``, ``:172 acquire``): candidates race to
CAS a lease object; the holder renews within the lease duration, standbys
take over when the renewal goes stale.  The scheduler and controller
manager run one active instance this way (SURVEY.md P6).

The lease is an annotated Event-kind object (the reference uses an
annotated Endpoints/ConfigMap the same way) with holder identity + renew
deadline in injected-clock time; everything is CAS so split-brain is
impossible at the store level."""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

from ..api import types as api
from ..api.meta import ObjectMeta
from ..store.store import AlreadyExistsError, ConflictError, NotFoundError
from .clientset import Clientset

LEASE_ANNOTATION = "control-plane.alpha.kubernetes.io/leader"


class LeaderElector:
    def __init__(
        self,
        clientset: Clientset,
        lock_name: str,
        identity: str,
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        # wall clock, NOT monotonic: renewTime is compared by OTHER
        # processes/hosts (the reference writes metav1.Time); monotonic
        # bases are boot-relative and would split-brain across hosts
        clock: Callable[[], float] = time.time,
    ):
        self.clientset = clientset
        self.lock_name = lock_name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self._clock = clock
        self._is_leader = False

    # -- lease record ------------------------------------------------------
    def _read(self) -> Optional[dict]:
        try:
            obj = self.clientset.events.get(self.lock_name, "kube-system")
        except NotFoundError:
            return None
        raw = obj.meta.annotations.get(LEASE_ANNOTATION)
        return json.loads(raw) if raw else None

    def _record(self) -> dict:
        return {
            "holderIdentity": self.identity,
            "renewTime": self._clock(),
            "leaseDurationSeconds": self.lease_duration,
        }

    # -- acquire / renew (leaderelection.go:172 acquire, :202 renew) -------
    def try_acquire_or_renew(self) -> bool:
        """One election round; returns True while this identity holds the
        lease.  Callers loop this (or use ``run``)."""
        now = self._clock()
        cur = self._read()
        if cur is None:
            try:
                self.clientset.events.create(
                    api.Event(
                        meta=ObjectMeta(
                            name=self.lock_name,
                            namespace="kube-system",
                            annotations={LEASE_ANNOTATION: json.dumps(self._record())},
                        ),
                        reason="LeaderElection",
                    )
                )
                self._is_leader = True
                return True
            except AlreadyExistsError:
                cur = self._read()

        holder = cur.get("holderIdentity") if cur else None
        expired = cur is None or now > cur.get("renewTime", 0) + cur.get(
            "leaseDurationSeconds", self.lease_duration
        )
        if holder != self.identity and not expired:
            self._is_leader = False
            return False

        # ours to renew, or stale and up for grabs — CAS it
        def _mutate(obj: api.Event) -> api.Event:
            inner = json.loads(obj.meta.annotations.get(LEASE_ANNOTATION, "{}") or "{}")
            inner_holder = inner.get("holderIdentity")
            inner_expired = now > inner.get("renewTime", 0) + inner.get(
                "leaseDurationSeconds", self.lease_duration
            )
            if inner_holder != self.identity and not inner_expired:
                raise _LostRace()
            obj.meta.annotations[LEASE_ANNOTATION] = json.dumps(self._record())
            return obj

        try:
            self.clientset.events.guaranteed_update(self.lock_name, _mutate, "kube-system")
            self._is_leader = True
            return True
        except (_LostRace, NotFoundError, ConflictError):
            self._is_leader = False
            return False

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    def release(self) -> None:
        """Voluntarily drop the lease (clean shutdown)."""
        if not self._is_leader:
            return

        def _mutate(obj: api.Event) -> api.Event:
            inner = json.loads(obj.meta.annotations.get(LEASE_ANNOTATION, "{}") or "{}")
            if inner.get("holderIdentity") == self.identity:
                inner["renewTime"] = -1e18  # instantly stale at any clock
                obj.meta.annotations[LEASE_ANNOTATION] = json.dumps(inner)
            return obj

        try:
            self.clientset.events.guaranteed_update(self.lock_name, _mutate, "kube-system")
        except NotFoundError:
            pass
        self._is_leader = False


class _LostRace(Exception):
    pass
