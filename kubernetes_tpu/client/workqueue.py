"""Rate-limited, deduplicating work queues.

Capability of ``client-go/util/workqueue``: items (keys) are deduped while
queued, in-flight items that are re-added are re-queued on done(), and
failures get per-item exponential backoff (``default_rate_limiters.go``).
This is the spine of every controller (SURVEY.md P3).

The delay machinery is virtual-time-friendly: pass a ``clock`` callable for
deterministic tests (the reference injects ``util/clock`` the same way).
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Callable, Hashable, Optional


class ExponentialBackoff:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: dict[Hashable, int] = {}
        self._mu = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._mu:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base_delay * (2**n), self.max_delay)

    def forget(self, item: Hashable) -> None:
        with self._mu:
            self._failures.pop(item, None)

    def retries(self, item: Hashable) -> int:
        with self._mu:
            return self._failures.get(item, 0)


class WorkQueue:
    """Dedup queue with the add/get/done discipline of ``workqueue.Type``."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._cond = threading.Condition()
        self._queue: deque[Hashable] = deque()
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._shutdown = False
        self._clock = clock
        # delayed adds: heap of (ready_time, seq, item)
        self._delayed: list[tuple[float, int, Hashable]] = []
        self._seq = 0
        self.rate_limiter = ExponentialBackoff()

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._cond.notify()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (self._clock() + delay, self._seq, item))
            self._cond.notify()

    def add_rate_limited(self, item: Hashable) -> None:
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Hashable) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self.rate_limiter.retries(item)

    def _drain_delayed_locked(self) -> Optional[float]:
        """Move ready delayed items into the queue; return wait time to the
        next delayed item, if any."""
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item not in self._dirty:
                self._dirty.add(item)
                if item not in self._processing:
                    self._queue.append(item)
        if self._delayed:
            return max(0.0, self._delayed[0][0] - now)
        return None

    def get(self, timeout: Optional[float] = None) -> Optional[Hashable]:
        """Blocking pop; returns None on shutdown or timeout."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                next_delay = self._drain_delayed_locked()
                if self._queue:
                    item = self._queue.popleft()
                    self._processing.add(item)
                    self._dirty.discard(item)
                    return item
                if self._shutdown:
                    return None
                wait = next_delay
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def try_get(self) -> Optional[Hashable]:
        return self.get(timeout=0)

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until at least one item is ready (without popping it),
        the queue shuts down, or the timeout elapses.  Returns whether an
        item is ready — the batch loop's accumulation wait: peek-and-wait
        instead of pop-and-requeue, so FIFO order is untouched."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                next_delay = self._drain_delayed_locked()
                if self._queue:
                    return True
                if self._shutdown:
                    return False
                wait = next_delay
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return False
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def drain_ready(self, max_n: Optional[int] = None) -> list:
        """Pop every currently-ready item under ONE lock acquisition (the
        batch scheduler's seam: item-at-a-time get/done costs two lock
        rounds per pod — 300k rounds per 150k-pod drain).  Items are
        returned already marked done (the caller owns the whole batch; a
        re-add during the batch re-queues normally via the dirty set)."""
        out: list = []
        with self._cond:
            self._drain_delayed_locked()
            while self._queue and (max_n is None or len(out) < max_n):
                item = self._queue.popleft()
                self._dirty.discard(item)
                out.append(item)
        return out

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def __len__(self) -> int:
        with self._cond:
            self._drain_delayed_locked()
            return len(self._queue)

    def pending_delayed(self) -> int:
        with self._cond:
            return len(self._delayed)

    def delayed_count(self) -> int:
        """Number of items still waiting in the delay heap AFTER moving
        ready ones into the queue — unlike :meth:`pending_delayed`, an
        item whose deadline passed is not counted.  O(ready-moves), no
        set materialization: the batch loop's accumulation window polls
        queue length every few ms, and building ``delayed_keys()``'s set
        per poll was pure overhead in the (typical) empty-heap case."""
        with self._cond:
            self._drain_delayed_locked()
            return len(self._delayed)

    def delayed_keys(self) -> set:
        """Items currently waiting in the delay heap (not yet ready)."""
        with self._cond:
            self._drain_delayed_locked()
            return {item for _, _, item in self._delayed}

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def is_shutdown(self) -> bool:
        with self._cond:
            return self._shutdown
