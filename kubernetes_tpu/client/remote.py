"""Remote store: the Store interface spoken over HTTP to an APIServer.

This is the transport seam promised in ``clientset.py``: a
``Clientset(RemoteStore(url))`` behaves identically to an in-process one —
informers, controllers, schedulers, and kubelets run unchanged against a
network apiserver (reference: ``client-go/rest`` under the generated
clientsets).  Watches consume the chunked JSON-lines stream and reconnect
from the last seen revision (reflector semantics, ``reflector.go:239``).

Failure handling (the part ``client-go/rest`` calls request.go retry +
``reflector.go`` relist):

- every request classifies its failure **honestly**: transport errors,
  5xx, and 429 are retryable (exponential backoff + seeded jitter, budget
  ``max_retries``); 4xx is fatal and maps to the typed store errors;
- a watch stream that breaks reconnects from the last seen revision with
  its own backoff; a resume refused with **410 Gone** cannot be healed by
  the stream itself — the watch emits a :data:`~..store.store.WATCH_GAP`
  sentinel and terminates, and the informer above relists (reflector.go's
  "too old resource version" → full LIST);
- shutdown closes the half-open HTTP response so the reader thread never
  leaks a socket past ``stop()``.

Every failure path is countable (``utils.metrics.ClientMetrics``) and
injectable (fault points ``remote.request`` / ``remote.watch.stream``) —
the fault matrix in tests/test_faults.py drives each one deterministically.
"""

from __future__ import annotations

import http.client
import json
import logging
import queue as queue_mod
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from .. import faults
from ..store import frames as frames_mod
from ..store.store import (
    AlreadyExistsError,
    ConflictError,
    ExpiredRevisionError,
    NotFoundError,
    WATCH_GAP,
    WatchEvent,
    object_key,
)
from ..utils import tracing
from ..utils.metrics import ClientMetrics

logger = logging.getLogger("kubernetes_tpu.client.remote")


class RemoteError(Exception):
    pass


class ForbiddenError(RemoteError):
    """HTTP 403 — authorization or admission said no.  A distinct type so
    callers (kubectl) surface 'Error from server (Forbidden)' instead of
    crashing on a generic RemoteError."""


class RetryExhaustedError(RemoteError):
    """A retryable failure outlived the retry budget.  Carries the last
    underlying error so callers can still see WHAT kept failing."""


# HTTP statuses worth re-trying: the server never started (or refused to
# start) the work.  Everything else in 4xx means the request itself is
# wrong — repeating it verbatim cannot succeed and hides real bugs.
RETRYABLE_STATUS = frozenset({429, 500, 502, 503, 504})


def _raise_for_status(body: dict) -> None:
    if body.get("kind") != "Status":
        return
    code, msg = body.get("code"), body.get("message", "")
    if code == 404:
        raise NotFoundError(msg)
    if code == 403:
        raise ForbiddenError(msg)
    if code == 409:
        if body.get("reason") == "AlreadyExists":
            raise AlreadyExistsError(msg)
        raise ConflictError(msg)
    if code == 410:
        raise ExpiredRevisionError(msg)
    raise RemoteError(f"{code}: {msg}")


def _parse_retry_after(headers) -> Optional[float]:
    """Server backoff hint from a 429/503 response (ISSUE 17: the
    apiserver's overload admission gate sends one).  Delta-seconds form
    only (RFC 7231 §7.1.3) — our servers send integers; the HTTP-date
    form is ignored.  None = no usable hint."""
    if headers is None:
        return None
    value = headers.get("Retry-After")
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        return None


class RemoteWatch:
    """Chunked-stream consumer with auto-reconnect from the last revision.

    Error classification in the read loop (``_run``):

    - **410 Gone** on resume: the server compacted past our bookmark; no
      reconnect can recover the lost deltas.  Emit ``WATCH_GAP`` and end
      the stream — the informer relists and builds a fresh watch.
    - **mid-frame failure** (a ``?frames=1`` line whose JSON parsed but
      whose columns are broken — length mismatch, corrupt revisions, or
      the injected ``phase=frame`` fault): the frame's events are lost as
      a UNIT and the bookmark cannot be trusted past it — same contract
      as 410: ``WATCH_GAP`` + stream end, the informer relists.  Never a
      silent partial apply, never a dead loop.
    - **stopped**: clean shutdown; the half-open response is closed by
      ``stop()`` so the blocking read unblocks instead of leaking.
    - anything else (connection reset, timeout, truncated JSON line, 5xx
      on reconnect): transient — count it, back off exponentially, and
      reconnect from ``resourceVersion=last_seen`` (reflector.go:239).
      The backoff resets once events flow again.
    """

    def __init__(self, base_url: str, kind: str, from_revision: Optional[int],
                 opener, resource: str, metrics: Optional[ClientMetrics] = None,
                 min_backoff: float = 0.05, max_backoff: float = 2.0,
                 sleep: Callable[[float], None] = time.sleep,
                 frames: bool = False,
                 label_selector: Optional[str] = None,
                 field_selector: Optional[str] = None):
        self._base = base_url
        self._resource = resource
        self._opener = opener
        # request column-packed frame delivery (?frames=1).  A pre-frame
        # server ignores the parameter and streams per-event lines — the
        # read loop handles both shapes, so this is a pure opt-in.
        self._frames = frames
        # server-side stream filtering (the LIST-then-WATCH selector
        # contract); with frames=True the server re-packs matching
        # sub-frames at the column level (ISSUE 19) instead of falling
        # back to per-event lines
        self._label_selector = label_selector
        self._field_selector = field_selector
        self.metrics = metrics or ClientMetrics()
        self._min_backoff = min_backoff
        self._max_backoff = max_backoff
        self._sleep = sleep
        self._queue: "queue_mod.Queue[Optional[WatchEvent]]" = queue_mod.Queue()
        self._stopped = threading.Event()
        self._last_rev = from_revision
        # the in-flight HTTP response: owned by the watch thread, closed
        # by stop() from the caller's thread — both sides under _resp_mu
        self._resp_mu = threading.Lock()
        self._resp = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _open_stream(self):
        url = f"{self._base}/api/v1/{self._resource}?watch=true&timeoutSeconds=5"
        if self._frames:
            url += "&frames=1"
        if self._label_selector:
            from urllib.parse import quote

            url += f"&labelSelector={quote(self._label_selector)}"
        if self._field_selector:
            from urllib.parse import quote

            url += f"&fieldSelector={quote(self._field_selector)}"
        if self._last_rev is not None:
            url += f"&resourceVersion={self._last_rev}"
        tr = tracing.current()
        # the (re)connect is the slow, failure-prone edge of the stream —
        # one span per dial, nothing per event
        with (tr.span("remote.watch.connect", cat="client",
                      resource=self._resource)
              if tr is not None else tracing.NULL_SPAN):
            faults.hit("remote.watch.stream", phase="connect",
                       resource=self._resource)
            return self._opener(url)

    def _run(self) -> None:
        backoff = self._min_backoff
        while not self._stopped.is_set():
            resp = None
            try:
                resp = self._open_stream()
                with self._resp_mu:
                    if self._stopped.is_set():
                        resp.close()
                        return
                    self._resp = resp
                for raw in resp:
                    if self._stopped.is_set():
                        return
                    line = raw.strip()
                    if not line:
                        continue
                    faults.hit("remote.watch.stream", phase="event",
                               resource=self._resource)
                    self.metrics.ingest_bytes.inc(len(line))
                    d = json.loads(line)
                    if d.get("type") == frames_mod.FRAME:
                        try:
                            faults.hit("remote.watch.stream", phase="frame",
                                       resource=self._resource)
                            frame = frames_mod.WatchFrame.from_wire(d)
                            # resourceVersion fence per frame: a replayed
                            # or reordered frame at-or-below the bookmark
                            # must not rewind it (its events were seen)
                            if (self._last_rev is not None
                                    and frame.revision <= self._last_rev):
                                continue
                        except Exception as e:  # noqa: BLE001 - classified
                            # mid-frame failure: the frame's events are
                            # lost as a unit and the bookmark is no longer
                            # trustworthy — gap + relist, like a 410
                            logger.warning(
                                "watch %s: undecodable frame (%s: %s) — "
                                "emitting gap for relist", self._resource,
                                type(e).__name__, e)
                            self.metrics.watch_errors.inc()
                            self.metrics.watch_gaps.inc()
                            tr = tracing.current()
                            if tr is not None:
                                tr.instant("remote.watch.gap",
                                           resource=self._resource,
                                           cause="bad-frame")
                            self._queue.put(WatchEvent(
                                WATCH_GAP, "", "", self._last_rev or 0, {}))
                            return
                        self._last_rev = frame.revision
                        backoff = self._min_backoff
                        self._queue.put(frame)
                        continue
                    ev = WatchEvent(
                        d["type"], d["kind"], d["key"], d["revision"], d["object"]
                    )
                    self._last_rev = ev.revision
                    backoff = self._min_backoff  # healthy stream: reset
                    self._queue.put(ev)
                # clean server-side timeout (timeoutSeconds elapsed):
                # immediate resume from the bookmark, not an error
            except Exception as e:
                if self._stopped.is_set():
                    return
                self.metrics.watch_errors.inc()
                if isinstance(e, urllib.error.HTTPError) and e.code == 410:
                    # resume refused: the server compacted past our
                    # bookmark.  The stream cannot self-heal — escalate
                    # to a relist through the informer and end.
                    logger.warning(
                        "watch %s: revision %s too old (410) — emitting "
                        "gap for relist", self._resource, self._last_rev)
                    self.metrics.watch_gaps.inc()
                    tr = tracing.current()
                    if tr is not None:
                        tr.instant("remote.watch.gap",
                                   resource=self._resource, cause="410")
                    self._queue.put(WatchEvent(
                        WATCH_GAP, "", "", self._last_rev or 0, {}))
                    return
                # a throttled reconnect (429/503) carries the server's
                # Retry-After hint: honor it — never shorter than our own
                # backoff, clamped to max_backoff (ISSUE 17)
                sleep_s = backoff
                if (isinstance(e, urllib.error.HTTPError)
                        and e.code in (429, 503)):
                    hint = _parse_retry_after(e.headers)
                    if hint is not None:
                        sleep_s = min(max(hint, backoff), self._max_backoff)
                        self.metrics.retry_after_honored.inc()
                # warn once on the transition into the broken state; the
                # retries of an outage that persists log at debug (a dead
                # server would otherwise emit a warning every backoff)
                log = (logger.warning if backoff == self._min_backoff
                       else logger.debug)
                log("watch %s: transient %s: %s — reconnecting from "
                    "revision %s in %.2fs", self._resource,
                    type(e).__name__, e, self._last_rev, sleep_s)
                self._sleep(sleep_s)
                backoff = min(backoff * 2, self._max_backoff)
                self.metrics.watch_reconnects.inc()
            finally:
                if resp is not None:
                    with self._resp_mu:
                        if self._resp is resp:
                            self._resp = None
                    try:
                        resp.close()
                    except Exception:  # noqa: BLE001 - close is best-effort
                        # the stream is being torn down either way; count
                        # it so a systematically failing close is visible
                        self.metrics.watch_close_errors.inc()

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self._queue.get(timeout=timeout)
        except queue_mod.Empty:
            return None

    def __iter__(self):
        while True:
            ev = self._queue.get()
            if ev is None:
                return
            yield ev

    def stop(self) -> None:
        self._stopped.set()
        # unblock the reader: close the half-open response NOW instead of
        # leaking it until the server-side timeout fires
        with self._resp_mu:
            resp, self._resp = self._resp, None
        if resp is not None:
            try:
                resp.close()
            except Exception:  # noqa: BLE001 - close is best-effort
                self.metrics.watch_close_errors.inc()
        self._queue.put(None)


class RemoteStore:
    """Store-interface adapter over the REST API."""

    def __init__(self, base_url: str, token: Optional[str] = None, timeout: float = 10.0,
                 ca_file: Optional[str] = None, client_cert: Optional[str] = None,
                 client_key: Optional[str] = None, binary: bool = False,
                 max_retries: int = 3, retry_backoff: float = 0.05,
                 retry_backoff_max: float = 2.0,
                 retry_seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 metrics: Optional[ClientMetrics] = None):
        """``ca_file`` pins the server CA for https:// servers;
        ``client_cert``/``client_key`` present an x509 client identity
        (reference kubeconfig certificate-authority / client-certificate).
        ``binary=True`` negotiates the compact binary wire form for
        resource bodies (reference protobuf content type).

        ``max_retries`` re-issues of a request after a retryable failure
        (5xx/429 for every verb; transport errors only when the request
        provably never ran — see ``_transport_retry_safe``), with
        exponential backoff from ``retry_backoff`` capped at
        ``retry_backoff_max`` and jittered per instance.  ``retry_seed``
        defaults to fresh entropy — a shared fixed seed would march every
        client through the SAME jitter sequence, re-synchronizing the
        thundering herd the jitter exists to break; pass a seed only in
        deterministic tests."""
        import random

        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.binary = binary
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self.retry_backoff_max = retry_backoff_max
        self._retry_rng = random.Random(retry_seed)
        self._sleep = sleep
        self.metrics = metrics or ClientMetrics()
        self._ssl_ctx = None
        if base_url.startswith("https://"):
            import ipaddress
            import ssl
            from urllib.parse import urlparse as _urlparse

            self._ssl_ctx = ssl.create_default_context(cafile=ca_file)
            if ca_file:
                try:
                    ipaddress.ip_address(_urlparse(base_url).hostname or "")
                    # IP-addressed clusters with a PINNED CA: certs rarely
                    # carry IP SANs; chain verification against the pinned
                    # CA still applies.  Without a pinned CA, hostname
                    # verification stays on — any public cert would
                    # otherwise pass.  DNS-named servers always verify.
                    self._ssl_ctx.check_hostname = False
                except ValueError:
                    pass
            if client_cert:
                self._ssl_ctx.load_cert_chain(client_cert, client_key)

    # -- http --------------------------------------------------------------
    def _open(self, url: str):
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=self.timeout, context=self._ssl_ctx)

    @staticmethod
    def _transport_retry_safe(method: str, e: BaseException) -> bool:
        """May this transport failure be retried without double-running
        the request?  Idempotent verbs (GET/HEAD): always.  Everything
        else only when the error proves the request never reached the
        server (connection refused) — a reset/timeout mid-POST may have
        committed server-side, and re-sending would turn one create into
        two (surfacing as a spurious AlreadyExists/Conflict to the
        caller).  client-go's retry gate draws the same line."""
        if method in ("GET", "HEAD"):
            return True
        reason = getattr(e, "reason", e)
        return isinstance(reason, ConnectionRefusedError)

    def _retry_delay(self, attempt: int,
                     retry_after: Optional[float] = None) -> float:
        """Exponential backoff with jitter in [0.5x, 1.5x) of the nominal
        step — deterministic per client (seeded RNG).  When the server
        sent a ``Retry-After`` hint (429/503), the hint replaces the
        exponential step — clamped to ``retry_backoff_max`` — with the
        SAME seeded jitter applied, so throttled herds still
        desynchronize instead of re-converging on the hint."""
        if retry_after is not None:
            nominal = min(max(retry_after, 0.0), self.retry_backoff_max)
        else:
            nominal = min(self.retry_backoff * (2 ** attempt), self.retry_backoff_max)
        return nominal * (0.5 + self._retry_rng.random())

    def _request_with_retries(self, send: Callable[[], "object"], method: str,
                              path: str):
        """Run ``send`` (one HTTP attempt) under the retry policy.

        Returns the live response object on success.  Raises the mapped
        typed error on a fatal classification, :class:`RetryExhaustedError`
        when the budget runs out.  ``send`` may raise HTTPError — a
        retryable status re-enters the loop, anything else is handed back
        to the caller for body decoding (the Status body carries the real
        reason: AlreadyExists vs Conflict, etc.)."""
        last_err: Optional[BaseException] = None
        retry_after: Optional[float] = None
        for attempt in range(self.max_retries + 1):
            if attempt > 0:
                tr = tracing.current()
                if tr is not None:
                    # retries are rare and each one is latency the caller
                    # ate — worth a point event; the happy path pays only
                    # the faults seam
                    tr.instant("remote.request.retry", method=method,
                               path=path, attempt=attempt)
                self._sleep(self._retry_delay(attempt - 1, retry_after))
                if retry_after is not None:
                    self.metrics.retry_after_honored.inc()
                retry_after = None
                self.metrics.remote_retries.inc()
            try:
                faults.hit("remote.request", method=method, path=path,
                           attempt=attempt)
                return send()
            except urllib.error.HTTPError as e:
                if e.code in RETRYABLE_STATUS:
                    # the throttle hint must be read BEFORE the drain
                    # below invalidates the response object
                    if e.code in (429, 503):
                        retry_after = _parse_retry_after(e.headers)
                    # drain + close: keep-alive sockets with pending bodies
                    # cannot be reused, and the retry opens a fresh one
                    try:
                        e.read()
                        e.close()
                    except Exception:  # noqa: BLE001 - drain is best-effort
                        # the retry opens a fresh connection regardless;
                        # count the failed drain so a pool that stops
                        # reusing sockets has a visible cause
                        self.metrics.remote_drain_errors.inc()
                    last_err = e
                    logger.warning("%s %s: retryable HTTP %d (attempt %d/%d)",
                                   method, path, e.code, attempt + 1,
                                   self.max_retries + 1)
                    continue
                # fatal 4xx: the caller decodes the Status body into the
                # typed error — retrying a malformed/forbidden/conflicting
                # request verbatim can never succeed
                self.metrics.remote_fatal.inc()
                raise
            except (urllib.error.URLError, TimeoutError, ConnectionError,
                    http.client.HTTPException, OSError) as e:
                if not self._transport_retry_safe(method, e):
                    # a non-idempotent request that MAY have committed:
                    # re-sending could double-run it — surface the
                    # transport error honestly instead
                    self.metrics.remote_fatal.inc()
                    raise
                last_err = e
                logger.warning("%s %s: transport error %s: %s (attempt %d/%d)",
                               method, path, type(e).__name__, e, attempt + 1,
                               self.max_retries + 1)
                continue
        self.metrics.remote_retry_exhausted.inc()
        raise RetryExhaustedError(
            f"{method} {path} failed after {self.max_retries + 1} attempts: "
            f"{type(last_err).__name__}: {last_err}")

    def _call(self, method: str, path: str, body=None,
              content_type: Optional[str] = None) -> dict:
        if content_type is not None:
            # explicit content type (PATCH negotiation) always sends JSON
            # bodies; binary Accept still applies to the response
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": content_type}
            if self.binary:
                from ..api import wire as binwire

                headers["Accept"] = binwire.CONTENT_TYPE
        elif self.binary:
            from ..api import wire as binwire

            data = binwire.encode(body) if body is not None else None
            headers = {"Content-Type": binwire.CONTENT_TYPE,
                       "Accept": binwire.CONTENT_TYPE}
        else:
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"}

        def send():
            req = urllib.request.Request(
                f"{self.base_url}{path}", data=data, method=method,
                headers=dict(headers),
            )
            if self.token:
                req.add_header("Authorization", f"Bearer {self.token}")
            return urllib.request.urlopen(req, timeout=self.timeout,
                                          context=self._ssl_ctx)

        try:
            with self._request_with_retries(send, method, path) as resp:
                out = self._decode(resp)
        except urllib.error.HTTPError as e:
            out = self._decode(e)
        _raise_for_status(out)
        return out

    @staticmethod
    def _decode(resp) -> dict:
        from ..api import wire as binwire

        raw = resp.read()
        if binwire.CONTENT_TYPE in (resp.headers.get("Content-Type") or ""):
            return binwire.decode(raw)
        return json.loads(raw.decode())

    def raw(self, method: str, path: str, body=None,
            timeout: Optional[float] = None) -> bytes:
        """Raw request carrying the store's credential and TLS context —
        the path for non-resource endpoints (discovery, /version,
        /healthz, subresource streams) so callers never hand-roll a
        urlopen that would drop the token or the pinned CA.  ``body`` may
        be a dict (JSON-encoded) or raw bytes (forwarded verbatim, e.g.
        file payloads through kubectl proxy).  Same retry policy as the
        resource verbs."""
        if isinstance(body, (bytes, bytearray)):
            data = bytes(body)
            headers = {}
        else:
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if data else {}

        def send():
            req = urllib.request.Request(
                f"{self.base_url}{path}", data=data, method=method,
                headers=dict(headers))
            if self.token:
                req.add_header("Authorization", f"Bearer {self.token}")
            return urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ssl_ctx)

        with self._request_with_retries(send, method, path) as resp:
            return resp.read()

    @staticmethod
    def _ns_path(namespace: str) -> str:
        return namespace if namespace else "-"

    @staticmethod
    def _resource(kind: str) -> str:
        from ..api.types import KIND_PLURALS

        plural = KIND_PLURALS.get(kind)
        if plural is None:
            raise RemoteError(f"unknown kind {kind}")
        return plural

    # -- Store interface ---------------------------------------------------
    def create(self, kind: str, obj: dict) -> dict:
        return self._call("POST", f"/api/v1/{self._resource(kind)}", obj)

    def create_many(self, kind: str, objs: list[dict]) -> list:
        """Batch create over the wire (``POST /{resource}:batch``): one
        request, one server-side store txn.  Mirrors Store.create_many's
        per-item best-effort contract (failed slots come back null).
        ONLY a 404 (NotFoundError: a pre-batch server has no such route)
        falls back to per-item creates — every other failure
        (RetryExhausted, Forbidden, 5xx) propagates: re-sending N
        individual requests against a failing or refusing server would
        amplify load and mask the real error."""
        try:
            out = self._call(
                "POST", f"/api/v1/{self._resource(kind)}:batch",
                {"items": objs})
            return out.get("items", [])
        except NotFoundError:
            results = []
            for obj in objs:
                try:
                    results.append(self.create(kind, obj))
                except Exception:  # noqa: BLE001 - per-item best effort
                    results.append(None)
            return results

    def get(self, kind: str, namespace: str, name: str) -> dict:
        return self._call(
            "GET",
            f"/api/v1/namespaces/{self._ns_path(namespace)}/{self._resource(kind)}/{name}",
        )

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[str] = None,
             field_selector: Optional[str] = None) -> tuple[list[dict], int]:
        from urllib.parse import quote

        path = f"/api/v1/{self._resource(kind)}"
        params = []
        if namespace is not None:
            params.append(f"namespace={quote(namespace)}")
        if label_selector:
            params.append(f"labelSelector={quote(label_selector)}")
        if field_selector:
            params.append(f"fieldSelector={quote(field_selector)}")
        if params:
            path += "?" + "&".join(params)
        out = self._call("GET", path)
        return out["items"], int(out["resourceVersion"])

    def list_columns(self, kind: str = "Pod",
                     namespace: Optional[str] = None):
        """Columnar LIST over the wire (``?columnar=1``): the server ships
        the packed batch payload (raw views + identity columns) in one
        response; the derived numeric/signature columns are rebuilt
        client-side.  Returns None when the server (or kind) lacks
        columnar support — callers fall back to :meth:`list`."""
        from ..store.columns import COLUMN_BATCH_KINDS

        batch_cls = COLUMN_BATCH_KINDS.get(kind)
        if batch_cls is None:
            return None
        from urllib.parse import quote

        path = f"/api/v1/{self._resource(kind)}?columnar=1"
        if namespace is not None:
            path += f"&namespace={quote(namespace)}"
        try:
            out = self._call("GET", path)
        except RemoteError:
            return None
        if out.get("kind") != f"{kind}ColumnBatch":
            return None  # pre-columnar server answered with plain items
        return batch_cls.from_wire(out)

    def patch(self, kind: str, namespace: str, name: str, patch,
              patch_type: str = "merge") -> dict:
        """Server-side PATCH (the reference's PATCH verb): the server
        applies the patch under its CAS loop — no read-modify-write round
        trips from the client."""
        from ..api.patch import CONTENT_TYPES

        ctype = next((c for c, t in CONTENT_TYPES.items() if t == patch_type),
                     "application/merge-patch+json")
        ns = self._ns_path(namespace)
        return self._call(
            "PATCH",
            f"/api/v1/namespaces/{ns}/{self._resource(kind)}/{name}",
            body=patch, content_type=ctype)

    def update(self, kind: str, obj: dict, expect_rev: Optional[int] = None, _trusted: bool = False) -> dict:
        meta = obj.get("metadata") or {}
        ns = self._ns_path(meta.get("namespace", "default"))
        name = meta.get("name", "")
        if expect_rev is not None:
            obj = dict(obj)
            obj["metadata"] = dict(meta)
            obj["metadata"]["resourceVersion"] = expect_rev
        return self._call(
            "PUT", f"/api/v1/namespaces/{ns}/{self._resource(kind)}/{name}", obj
        )

    def guaranteed_update(self, kind: str, namespace: str, name: str, mutate: Callable[[dict], dict]) -> dict:
        while True:
            cur = self.get(kind, namespace, name)
            rev = int(cur["metadata"]["resourceVersion"])
            new = mutate(cur)
            try:
                return self.update(kind, new, expect_rev=rev)
            except ConflictError:
                continue

    def delete(self, kind: str, namespace: str, name: str, expect_rev: Optional[int] = None) -> dict:
        return self._call(
            "DELETE",
            f"/api/v1/namespaces/{self._ns_path(namespace)}/{self._resource(kind)}/{name}",
        )

    def bind_many(self, items: list[tuple[str, str, str]]) -> list[Optional[str]]:
        out = self._call(
            "POST",
            "/api/v1/bindings:batch",
            {
                "bindings": [
                    {"podNamespace": ns, "podName": name, "nodeName": node}
                    for ns, name, node in items
                ]
            },
        )
        return out["errors"]

    def watch(self, kind: Optional[str] = None, from_revision: Optional[int] = None,
              frames: bool = False,
              label_selector: Optional[str] = None,
              field_selector: Optional[str] = None) -> RemoteWatch:
        if kind is None:
            raise RemoteError("remote watch requires a kind")
        return RemoteWatch(self.base_url, kind, from_revision, self._open,
                           self._resource(kind), metrics=self.metrics,
                           sleep=self._sleep, frames=frames,
                           label_selector=label_selector,
                           field_selector=field_selector)
