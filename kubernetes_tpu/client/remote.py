"""Remote store: the Store interface spoken over HTTP to an APIServer.

This is the transport seam promised in ``clientset.py``: a
``Clientset(RemoteStore(url))`` behaves identically to an in-process one —
informers, controllers, schedulers, and kubelets run unchanged against a
network apiserver (reference: ``client-go/rest`` under the generated
clientsets).  Watches consume the chunked JSON-lines stream and reconnect
from the last seen revision (reflector semantics, ``reflector.go:239``).
"""

from __future__ import annotations

import json
import queue as queue_mod
import threading
import urllib.error
import urllib.request
from typing import Callable, Optional

from ..store.store import (
    AlreadyExistsError,
    ConflictError,
    ExpiredRevisionError,
    NotFoundError,
    WatchEvent,
    object_key,
)


class RemoteError(Exception):
    pass


class ForbiddenError(RemoteError):
    """HTTP 403 — authorization or admission said no.  A distinct type so
    callers (kubectl) surface 'Error from server (Forbidden)' instead of
    crashing on a generic RemoteError."""


def _raise_for_status(body: dict) -> None:
    if body.get("kind") != "Status":
        return
    code, msg = body.get("code"), body.get("message", "")
    if code == 404:
        raise NotFoundError(msg)
    if code == 403:
        raise ForbiddenError(msg)
    if code == 409:
        if body.get("reason") == "AlreadyExists":
            raise AlreadyExistsError(msg)
        raise ConflictError(msg)
    if code == 410:
        raise ExpiredRevisionError(msg)
    raise RemoteError(f"{code}: {msg}")


class RemoteWatch:
    """Chunked-stream consumer with auto-reconnect from the last revision."""

    def __init__(self, base_url: str, kind: str, from_revision: Optional[int], opener, resource: str):
        self._base = base_url
        self._resource = resource
        self._opener = opener
        self._queue: "queue_mod.Queue[Optional[WatchEvent]]" = queue_mod.Queue()
        self._stopped = threading.Event()
        self._last_rev = from_revision
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stopped.is_set():
            url = f"{self._base}/api/v1/{self._resource}?watch=true&timeoutSeconds=5"
            if self._last_rev is not None:
                url += f"&resourceVersion={self._last_rev}"
            try:
                with self._opener(url) as resp:
                    for raw in resp:
                        if self._stopped.is_set():
                            return
                        line = raw.strip()
                        if not line:
                            continue
                        d = json.loads(line)
                        ev = WatchEvent(
                            d["type"], d["kind"], d["key"], d["revision"], d["object"]
                        )
                        self._last_rev = ev.revision
                        self._queue.put(ev)
            except Exception:
                if self._stopped.is_set():
                    return
                import time

                time.sleep(0.05)  # transient; reconnect from last revision

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self._queue.get(timeout=timeout)
        except queue_mod.Empty:
            return None

    def __iter__(self):
        while True:
            ev = self._queue.get()
            if ev is None:
                return
            yield ev

    def stop(self) -> None:
        self._stopped.set()
        self._queue.put(None)


class RemoteStore:
    """Store-interface adapter over the REST API."""

    def __init__(self, base_url: str, token: Optional[str] = None, timeout: float = 10.0,
                 ca_file: Optional[str] = None, client_cert: Optional[str] = None,
                 client_key: Optional[str] = None, binary: bool = False):
        """``ca_file`` pins the server CA for https:// servers;
        ``client_cert``/``client_key`` present an x509 client identity
        (reference kubeconfig certificate-authority / client-certificate).
        ``binary=True`` negotiates the compact binary wire form for
        resource bodies (reference protobuf content type)."""
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.binary = binary
        self._ssl_ctx = None
        if base_url.startswith("https://"):
            import ipaddress
            import ssl
            from urllib.parse import urlparse as _urlparse

            self._ssl_ctx = ssl.create_default_context(cafile=ca_file)
            if ca_file:
                try:
                    ipaddress.ip_address(_urlparse(base_url).hostname or "")
                    # IP-addressed clusters with a PINNED CA: certs rarely
                    # carry IP SANs; chain verification against the pinned
                    # CA still applies.  Without a pinned CA, hostname
                    # verification stays on — any public cert would
                    # otherwise pass.  DNS-named servers always verify.
                    self._ssl_ctx.check_hostname = False
                except ValueError:
                    pass
            if client_cert:
                self._ssl_ctx.load_cert_chain(client_cert, client_key)

    # -- http --------------------------------------------------------------
    def _open(self, url: str):
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=self.timeout, context=self._ssl_ctx)

    def _call(self, method: str, path: str, body=None,
              content_type: Optional[str] = None) -> dict:
        if content_type is not None:
            # explicit content type (PATCH negotiation) always sends JSON
            # bodies; binary Accept still applies to the response
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": content_type}
            if self.binary:
                from ..api import wire as binwire

                headers["Accept"] = binwire.CONTENT_TYPE
        elif self.binary:
            from ..api import wire as binwire

            data = binwire.encode(body) if body is not None else None
            headers = {"Content-Type": binwire.CONTENT_TYPE,
                       "Accept": binwire.CONTENT_TYPE}
        else:
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"}
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, method=method, headers=headers,
        )
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl_ctx) as resp:
                out = self._decode(resp)
        except urllib.error.HTTPError as e:
            out = self._decode(e)
        _raise_for_status(out)
        return out

    @staticmethod
    def _decode(resp) -> dict:
        from ..api import wire as binwire

        raw = resp.read()
        if binwire.CONTENT_TYPE in (resp.headers.get("Content-Type") or ""):
            return binwire.decode(raw)
        return json.loads(raw.decode())

    def raw(self, method: str, path: str, body=None,
            timeout: Optional[float] = None) -> bytes:
        """Raw request carrying the store's credential and TLS context —
        the path for non-resource endpoints (discovery, /version,
        /healthz, subresource streams) so callers never hand-roll a
        urlopen that would drop the token or the pinned CA.  ``body`` may
        be a dict (JSON-encoded) or raw bytes (forwarded verbatim, e.g.
        file payloads through kubectl proxy)."""
        if isinstance(body, (bytes, bytearray)):
            data = bytes(body)
            headers = {}
        else:
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if data else {}
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, method=method, headers=headers)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(
            req, timeout=timeout or self.timeout, context=self._ssl_ctx
        ) as resp:
            return resp.read()

    @staticmethod
    def _ns_path(namespace: str) -> str:
        return namespace if namespace else "-"

    @staticmethod
    def _resource(kind: str) -> str:
        from ..api.types import KIND_PLURALS

        plural = KIND_PLURALS.get(kind)
        if plural is None:
            raise RemoteError(f"unknown kind {kind}")
        return plural

    # -- Store interface ---------------------------------------------------
    def create(self, kind: str, obj: dict) -> dict:
        return self._call("POST", f"/api/v1/{self._resource(kind)}", obj)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        return self._call(
            "GET",
            f"/api/v1/namespaces/{self._ns_path(namespace)}/{self._resource(kind)}/{name}",
        )

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[str] = None,
             field_selector: Optional[str] = None) -> tuple[list[dict], int]:
        from urllib.parse import quote

        path = f"/api/v1/{self._resource(kind)}"
        params = []
        if namespace is not None:
            params.append(f"namespace={quote(namespace)}")
        if label_selector:
            params.append(f"labelSelector={quote(label_selector)}")
        if field_selector:
            params.append(f"fieldSelector={quote(field_selector)}")
        if params:
            path += "?" + "&".join(params)
        out = self._call("GET", path)
        return out["items"], int(out["resourceVersion"])

    def patch(self, kind: str, namespace: str, name: str, patch,
              patch_type: str = "merge") -> dict:
        """Server-side PATCH (the reference's PATCH verb): the server
        applies the patch under its CAS loop — no read-modify-write round
        trips from the client."""
        from ..api.patch import CONTENT_TYPES

        ctype = next((c for c, t in CONTENT_TYPES.items() if t == patch_type),
                     "application/merge-patch+json")
        ns = self._ns_path(namespace)
        return self._call(
            "PATCH",
            f"/api/v1/namespaces/{ns}/{self._resource(kind)}/{name}",
            body=patch, content_type=ctype)

    def update(self, kind: str, obj: dict, expect_rev: Optional[int] = None, _trusted: bool = False) -> dict:
        meta = obj.get("metadata") or {}
        ns = self._ns_path(meta.get("namespace", "default"))
        name = meta.get("name", "")
        if expect_rev is not None:
            obj = dict(obj)
            obj["metadata"] = dict(meta)
            obj["metadata"]["resourceVersion"] = expect_rev
        return self._call(
            "PUT", f"/api/v1/namespaces/{ns}/{self._resource(kind)}/{name}", obj
        )

    def guaranteed_update(self, kind: str, namespace: str, name: str, mutate: Callable[[dict], dict]) -> dict:
        while True:
            cur = self.get(kind, namespace, name)
            rev = int(cur["metadata"]["resourceVersion"])
            new = mutate(cur)
            try:
                return self.update(kind, new, expect_rev=rev)
            except ConflictError:
                continue

    def delete(self, kind: str, namespace: str, name: str, expect_rev: Optional[int] = None) -> dict:
        return self._call(
            "DELETE",
            f"/api/v1/namespaces/{self._ns_path(namespace)}/{self._resource(kind)}/{name}",
        )

    def bind_many(self, items: list[tuple[str, str, str]]) -> list[Optional[str]]:
        out = self._call(
            "POST",
            "/api/v1/bindings:batch",
            {
                "bindings": [
                    {"podNamespace": ns, "podName": name, "nodeName": node}
                    for ns, name, node in items
                ]
            },
        )
        return out["errors"]

    def watch(self, kind: Optional[str] = None, from_revision: Optional[int] = None) -> RemoteWatch:
        if kind is None:
            raise RemoteError("remote watch requires a kind")
        return RemoteWatch(self.base_url, kind, from_revision, self._open, self._resource(kind))
