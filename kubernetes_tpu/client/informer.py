"""Shared informer: LIST+WATCH → local indexed cache → handler fan-out.

The reference's list-watch-cache stack
(``client-go/tools/cache``: ``reflector.go:239 ListAndWatch``,
``shared_informer.go:182 Run`` + ``processorListener :537``) collapsed into
one component: list to seed the cache at a revision, watch from that
revision, apply deltas to an indexed local store, and fan events out to any
number of handlers (SURVEY.md P4).

Two drive modes:

- ``start()`` — background thread, production-shaped;
- ``pump()`` — synchronously drain pending watch events on the caller's
  thread.  Deterministic tests and single-threaded control loops use this;
  it is the informer analogue of running the event loop manually.

Objects handed to handlers are shared and MUST NOT be mutated.  With
``mutation_detector=True`` the informer snapshots each object and panics on
divergence — the reference's ``KUBE_CACHE_MUTATION_DETECTOR``
(``tools/cache/mutation_detector.go``).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from .. import faults
from ..api import lazy as lazy_mod
from ..api import types as api
from ..store.frames import FRAME, WatchFrame
from ..store.store import (
    ADDED,
    DELETED,
    MODIFIED,
    WATCH_GAP,
    ExpiredRevisionError,
    WatchEvent,
)
from ..utils import tracing
from ..utils.metrics import DEFAULT_CLIENT_METRICS, ClientMetrics
from .clientset import TypedClient

logger = logging.getLogger("kubernetes_tpu.client.informer")


class Handler:
    def __init__(
        self,
        on_add: Optional[Callable] = None,
        on_update: Optional[Callable] = None,
        on_delete: Optional[Callable] = None,
        on_batch: Optional[Callable] = None,
    ):
        self.on_add = on_add or (lambda obj: None)
        self.on_update = on_update or (lambda old, new: None)
        self.on_delete = on_delete or (lambda obj: None)
        # batch-aware handlers receive a whole watch frame in ONE call:
        # ``on_batch(frame, deltas)`` with deltas = [(type, old, new, i)]
        # (i indexes the frame's columns — dropped/fenced events are
        # absent).  Handlers without it get the per-event callbacks for
        # every framed event, so frames never change handler semantics.
        self.on_batch = on_batch


class SharedInformer:
    def __init__(self, client: TypedClient, mutation_detector: bool = False,
                 metrics: Optional[ClientMetrics] = None,
                 compact_on_resync: bool = False):
        self._client = client
        self.kind = client.kind
        self._handlers: list[Handler] = []
        self._cache: dict[str, object] = {}  # key -> typed object
        self._mu = threading.RLock()
        self._synced = threading.Event()
        self._watch = None
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._mutation_detector = mutation_detector
        self._snapshots: dict[str, dict] = {}
        self.last_revision = 0
        self.metrics = metrics or DEFAULT_CLIENT_METRICS
        # per-instance recovery audit trail (the fault matrix reads this)
        # + ingest-decode observability (the churn bench deltas decode_s
        # per wave; decode_errors is the informer.decode recovery signal)
        self.stats = {"relists": 0, "dropped_events": 0, "handler_errors": 0,
                      "relist_failures": 0, "decode_errors": 0,
                      "decoded_events": 0, "decode_s": 0.0,
                      # batched watch frames (ISSUE 6): frames applied,
                      # events they carried, frames lost whole (→ gap),
                      # cumulative apply time (cache + handler fan-out) —
                      # the scheduler's per-wave pump_apply delta source —
                      # and promote-and-drop-raw sweeps
                      "frames": 0, "frame_events": 0, "batch_errors": 0,
                      "apply_s": 0.0, "compactions": 0}
        # ROADMAP carried item (ISSUE 7 satellite): with the flag on,
        # every successful relist/resync ends with a promote-and-drop-raw
        # sweep, so a long-lived deployment's cache stops pinning wire
        # payloads without anyone calling compact_cache() by hand
        self.compact_on_resync = compact_on_resync
        # serializes relist(): a resync timer tick racing a GAP
        # escalation must not build two watches and leak the loser
        self._relist_mu = threading.Lock()
        # set when a relist attempt failed (apiserver briefly down):
        # pump()/_run_loop retry on their next turn instead of leaving
        # the informer wedged on a dead watch serving a frozen cache
        self._gap_pending = False

    # -- registration ------------------------------------------------------
    def add_handler(self, handler: Handler) -> None:
        # snapshot under the lock, replay OUTSIDE it (the same contract
        # _deliver's callers follow): handler code under _mu could call
        # back into get()/list() and deadlock, or stall every other
        # informer client behind a slow on_add.  A delta applied between
        # the release and the replay may reach the handler before its
        # replayed add — the same at-least-once ordering client-go's
        # shared informers give a late-registered handler.
        with self._mu:
            self._handlers.append(handler)
            replay = list(self._cache.values()) if self._synced.is_set() else []
        for obj in replay:
            self._deliver(handler.on_add, obj)

    # -- cache reads (the Lister/Indexer surface) --------------------------
    def get(self, key: str):
        with self._mu:
            return self._cache.get(key)

    def list(self) -> list:
        with self._mu:
            return list(self._cache.values())

    def keys(self) -> list[str]:
        with self._mu:
            return list(self._cache.keys())

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- lifecycle ---------------------------------------------------------
    def _list(self):
        """LIST through the cheapest available path: the store's packed
        column batch (zero-copy views + precomputed identity columns)
        when the transport offers one, else lazy decode-on-access views,
        else the eager typed decode (the compatibility oracle, and the
        ``--ab-pump`` A arm).  Returns (objs, revision, keys-or-None) —
        keys ride along from the column batch so seeding skips even the
        per-object meta decode."""
        if lazy_mod.ENABLED:
            lc = getattr(self._client, "list_columns", None)
            batch = lc() if lc is not None else None
            if batch is not None:
                # kind-agnostic: Pod and Node batches both expose
                # objects()/keys (store/columns.py COLUMN_BATCH_KINDS)
                return batch.objects(), batch.revision, batch.keys
            ll = getattr(self._client, "list_lazy", None)
            if ll is not None:
                objs, rev = ll()
                return objs, rev, None
        objs, rev = self._client.list()
        return objs, rev, None

    def _watch_from(self, rev: int):
        """Build the watch, opting into column-packed frame delivery when
        the client speaks it (the informer is frame-aware; clients that
        predate the parameter degrade to per-event)."""
        try:
            return self._client.watch(from_revision=rev, frames=True)
        except TypeError:
            return self._client.watch(from_revision=rev)

    def _seed(self) -> None:
        objs, rev, keys = self._list()
        with self._mu:
            self._cache = (dict(zip(keys, objs)) if keys is not None
                           else {o.meta.key: o for o in objs})
            if self._mutation_detector:
                self._snapshots = {o.meta.key: o.to_dict() for o in objs}
            self.last_revision = rev
            self._watch = self._watch_from(rev)
            handlers = list(self._handlers)
            objs_now = list(self._cache.values())
        for h in handlers:
            for o in objs_now:
                # isolated like every later delivery: a handler that
                # panics on the seed fan-out (e.g. promoting a payload it
                # chokes on) must not wedge its peers or the seed
                self._deliver(h.on_add, o)
        self._synced.set()

    def start(self) -> None:
        """Seed synchronously, then consume the watch on a daemon thread."""
        self._seed()
        self._thread = threading.Thread(target=self._run_loop, daemon=True)
        self._thread.start()

    def start_manual(self) -> None:
        """Seed synchronously; caller drives with pump()."""
        self._seed()

    def stop(self) -> None:
        self._stopped.set()
        if self._watch is not None:
            self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run_loop(self) -> None:
        while not self._stopped.is_set():
            if self._gap_pending:
                self._try_relist()  # the 0.2s get below paces retries
            ev = self._watch.get(timeout=0.2)
            if ev is None:
                continue
            try:
                self._apply(ev)
            except CacheMutationError:
                raise  # the detector's whole point is to panic
            except Exception:
                if self._stopped.is_set():
                    return
                # the watch loop is the informer's heartbeat: one bad
                # delta (or injected delivery failure) must not end it
                logger.exception("informer %s: failed to apply %s %s",
                                 self.kind, ev.type, ev.key)

    def pump(self, max_events: Optional[int] = None) -> int:
        """Synchronously apply all (or up to max_events) pending events.
        A no-op when the watch thread owns the stream (mixed drivers —
        e.g. a clock tick inside a threaded daemon — must not compete
        for events)."""
        if self._thread is not None:
            return 0
        if self._watch is None:
            self._seed()
        if self._gap_pending:
            self._try_relist()  # one retry per pump: bounded, caller-paced
        n = 0
        while max_events is None or n < max_events:
            ev = self._watch.get(timeout=0)
            if ev is None:
                break
            self._apply(ev)
            # a frame counts for the events it carried (max_events stays
            # a soft bound: frames are never split mid-apply)
            n += len(ev) if ev.type == FRAME else 1
        return n

    # -- relist (reflector 410 fallback + resync) --------------------------
    def relist(self) -> None:
        """Full LIST → cache diff → watch restart (``reflector.go``'s
        "too old resource version" fallback, doubling as the resync
        period).  Handlers see the diff as ordinary add/update/delete
        callbacks — exactly what they'd have seen had the lost deltas
        been delivered — so a cache gap of any size reconverges in one
        call.  Safe to call periodically: an in-sync informer diffs to
        nothing and only pays the LIST.

        Crash-safe ordering: the new LIST + watch are built BEFORE the
        old watch is touched, so a failure here (apiserver briefly down)
        leaves the informer exactly as it was — and ``_gap_pending``
        makes pump()/the watch loop retry, never wedging on a dead
        stream.  ``_relist_mu`` serializes concurrent callers (resync
        timer vs GAP escalation): the loser waits and then relists
        against the fresh state instead of leaking a live watch."""
        tr = tracing.current()
        with (tr.span("informer.relist", cat="ingest", kind=self.kind)
              if tr is not None else tracing.NULL_SPAN):
            self._relist_inner()
        if self.compact_on_resync:
            self.compact_cache()

    def _relist_inner(self) -> None:
        with self._relist_mu:
            attempts = 0
            while True:
                objs, rev, keys = self._list()
                try:
                    new_watch = self._watch_from(rev)
                    break
                except ExpiredRevisionError:
                    # the window slid past rev between LIST and WATCH —
                    # possible only under extreme write pressure; relist
                    attempts += 1
                    if attempts >= 5:
                        raise
            new_cache = (dict(zip(keys, objs)) if keys is not None
                         else {o.meta.key: o for o in objs})
            with self._mu:
                old_watch = self._watch
                old_cache = self._cache
                self._cache = new_cache
                if self._mutation_detector:
                    self._snapshots = {o.meta.key: o.to_dict() for o in objs}
                self.last_revision = max(self.last_revision, rev)
                self._watch = new_watch
                handlers = list(self._handlers)
                self.stats["relists"] += 1
                self._gap_pending = False
            if old_watch is not None:
                # events the old stream delivered after our LIST are at
                # revisions the new watch replays too — dropping its
                # queue loses nothing
                old_watch.stop()
        self.metrics.informer_relists.inc()
        for key, obj in new_cache.items():
            old = old_cache.get(key)
            if old is None:
                for h in handlers:
                    self._deliver(h.on_add, obj)
            elif lazy_mod.resource_version_of(old) != lazy_mod.resource_version_of(obj):
                # the raw-aware read keeps the steady-state resync diff
                # (5k nodes + 150k pods) from decoding every object's meta
                for h in handlers:
                    self._deliver(h.on_update, old, obj)
        for key, old in old_cache.items():
            if key not in new_cache:
                for h in handlers:
                    self._deliver(h.on_delete, old)

    # alias: the reference's resyncPeriod is this same relist, on a timer
    resync = relist

    def _try_relist(self) -> bool:
        """Relist, absorbing failure into ``_gap_pending`` so the next
        pump()/loop turn retries — a relist that fails because the
        apiserver is briefly unreachable must degrade to 'stale until it
        returns', never to 'wedged forever'."""
        try:
            self.relist()
            return True
        except Exception:
            with self._mu:
                self._gap_pending = True
                self.stats["relist_failures"] += 1
            logger.exception(
                "informer %s: relist failed — will retry", self.kind)
            return False

    def _deliver(self, fn, *args) -> None:
        """One handler callback, isolated: a panicking handler is counted
        and logged, never allowed to wedge delivery to its peers or kill
        the watch loop (processorListener's crash isolation)."""
        try:
            fn(*args)
        except Exception:
            with self._mu:
                self.stats["handler_errors"] += 1
            self.metrics.informer_handler_errors.inc()
            logger.exception("informer %s: handler error (isolated)", self.kind)

    # -- delta application -------------------------------------------------
    def _apply(self, ev) -> None:
        if ev.type == FRAME:
            # a column-packed batch: one lock hold for the whole frame
            return self._apply_batch(ev)
        if ev.type == WATCH_GAP:
            # the transport admitted it lost continuity (410 on resume):
            # no payload to apply; rebuild from a fresh LIST
            self._try_relist()
            return
        tr = tracing.current()
        with (tr.span("informer.event.apply", cat="ingest", kind=self.kind,
                      key=ev.key, type=ev.type)
              if tr is not None and tr.verbose else tracing.NULL_SPAN):
            t_apply = time.perf_counter()
            try:
                self._apply_event(ev)
            finally:
                # the scheduler deltas this per wave (pump APPLICATION time)
                dt = time.perf_counter() - t_apply
                with self._mu:
                    self.stats["apply_s"] += dt

    def _apply_event(self, ev: WatchEvent) -> None:
        if ev.revision <= self.last_revision:
            # revision fence: a straggler from a watch that a relist
            # already superseded (the LIST at last_revision subsumes it)
            # must not overwrite the fresher cache
            return
        fault = faults.hit("informer.deliver", kind=self.kind, key=ev.key,
                           type=ev.type)
        if fault is not None and fault.mode == "drop":
            # lossy delivery: the delta silently never happens — the
            # cache diverges until the next relist/resync reconverges it
            with self._mu:
                self.stats["dropped_events"] += 1
            self.metrics.informer_dropped_events.inc()
            return
        t_decode = time.perf_counter()
        try:
            faults.hit("informer.decode", kind=self.kind, key=ev.key,
                       type=ev.type)
            if lazy_mod.ENABLED:
                # zero-copy: the event payload becomes the object's wire
                # backing; typed fields materialize on first touch
                obj = lazy_mod.wrap(self._client._cls, ev.object)
            else:
                obj = self._client._cls.from_dict(ev.object)
        except Exception:
            # a payload this informer cannot decode (or an injected
            # decode fault) loses the delta, never the watch loop: mark
            # the gap so the next pump/loop turn relists — the informer
            # degrades to 'stale until relist', not 'wedged'
            with self._mu:
                self.stats["decode_errors"] += 1
                self._gap_pending = True
            self.metrics.informer_decode_errors.inc()
            logger.exception("informer %s: failed to decode %s %s — "
                             "relist scheduled", self.kind, ev.type, ev.key)
            return
        dt = time.perf_counter() - t_decode
        with self._mu:
            self.stats["decoded_events"] += 1
            self.stats["decode_s"] += dt
            old = self._cache.get(ev.key)
            if self._mutation_detector and old is not None:
                snap = self._snapshots.get(ev.key)
                if snap is not None and old.to_dict() != snap:
                    raise CacheMutationError(
                        f"{self.kind} {ev.key} was mutated in the informer cache"
                    )
            if ev.type == DELETED:
                self._cache.pop(ev.key, None)
                self._snapshots.pop(ev.key, None)
            else:
                self._cache[ev.key] = obj
                if self._mutation_detector:
                    self._snapshots[ev.key] = obj.to_dict()
            self.last_revision = max(self.last_revision, ev.revision)
            handlers = list(self._handlers)
        for h in handlers:
            if ev.type == ADDED:
                self._deliver(h.on_add, obj)
            elif ev.type == MODIFIED:
                self._deliver(h.on_update, old, obj)
            elif ev.type == DELETED:
                self._deliver(h.on_delete, old if old is not None else obj)

    # -- batch (frame) application -----------------------------------------
    def _decode_frame(self, frame: WatchFrame, fence: int) -> tuple:
        """Decode a frame's payloads OUTSIDE the cache lock.  Returns
        (decoded, dropped, decode_errors, decode_s) where decoded is
        [(i, type, key, revision, obj-or-None)] — per-event faults keep
        their per-event semantics: a dropped delivery or an undecodable
        payload loses THAT delta (gap marked for decode), never the
        frame."""
        decoded = []
        dropped = 0
        decode_errors = 0
        t_decode = time.perf_counter()
        cls = self._client._cls
        for i in range(len(frame)):
            etype, key, rev = frame.types[i], frame.keys[i], frame.revisions[i]
            if rev <= fence:
                continue  # straggler events inside a superseded frame
            fault = faults.hit("informer.deliver", kind=self.kind, key=key,
                               type=etype)
            if fault is not None and fault.mode == "drop":
                dropped += 1
                continue
            try:
                faults.hit("informer.decode", kind=self.kind, key=key,
                           type=etype)
                raw = frame.objects[i]
                obj = (lazy_mod.wrap(cls, raw) if lazy_mod.ENABLED
                       else cls.from_dict(raw))
            except Exception:
                decode_errors += 1
                logger.exception("informer %s: failed to decode %s %s in a "
                                 "frame — relist scheduled", self.kind,
                                 etype, key)
                continue
            decoded.append((i, etype, key, rev, obj))
        return decoded, dropped, decode_errors, time.perf_counter() - t_decode

    def _apply_batch(self, frame: WatchFrame) -> None:
        """Apply one column-packed frame: decode outside the lock, then
        the WHOLE batch lands in the cache under ONE lock hold, and each
        handler receives it in one isolated call (``on_batch``) or as the
        usual per-event callbacks.  A failure before any event applied
        (the ``informer.apply_batch`` fault, broken columns) loses the
        frame as a unit and marks a gap — the existing relist path heals
        it, exactly like a decode failure or a 410.

        The frame-apply span carries the emitting txn's correlation id
        (ISSUE 7): the store's txn span, this span, and the scheduler's
        confirm span (which runs inside this one's handler fan-out) all
        share it, so one trace shows the store→informer→confirm path."""
        tr = tracing.current()
        with (tr.span("informer.frame.apply", cat="ingest", kind=self.kind,
                      txn=frame.txn, events=len(frame))
              if tr is not None else tracing.NULL_SPAN) as sp:
            self._apply_batch_inner(frame, sp)

    def _apply_batch_inner(self, frame: WatchFrame, sp) -> None:
        t_apply = time.perf_counter()
        try:
            faults.hit("informer.apply_batch", kind=self.kind, n=len(frame))
            decoded, dropped, decode_errors, decode_s = self._decode_frame(
                frame, self.last_revision)
        except Exception:
            with self._mu:
                self.stats["batch_errors"] += 1
                self._gap_pending = True
            self.metrics.informer_frame_errors.inc()
            logger.exception(
                "informer %s: failed to apply a %d-event frame — relist "
                "scheduled", self.kind, len(frame))
            return
        if dropped:
            self.metrics.informer_dropped_events.inc(dropped)
        if decode_errors:
            self.metrics.informer_decode_errors.inc(decode_errors)
        applied: list = []
        with self._mu:
            self.stats["frames"] += 1
            self.stats["dropped_events"] += dropped
            self.stats["decode_errors"] += decode_errors
            if decode_errors:
                self._gap_pending = True
            self.stats["decoded_events"] += len(decoded)
            self.stats["decode_s"] += decode_s
            for i, etype, key, rev, obj in decoded:
                if rev <= self.last_revision:
                    continue  # a concurrent relist superseded this event
                old = self._cache.get(key)
                if self._mutation_detector and old is not None:
                    snap = self._snapshots.get(key)
                    if snap is not None and old.to_dict() != snap:
                        raise CacheMutationError(
                            f"{self.kind} {key} was mutated in the informer cache"
                        )
                if etype == DELETED:
                    self._cache.pop(key, None)
                    self._snapshots.pop(key, None)
                else:
                    self._cache[key] = obj
                    if self._mutation_detector:
                        self._snapshots[key] = obj.to_dict()
                self.last_revision = max(self.last_revision, rev)
                applied.append((etype, old, obj, i))
            self.stats["frame_events"] += len(applied)
            handlers = list(self._handlers)
        for h in handlers:
            if h.on_batch is not None:
                # one isolated call per handler: a batch-aware handler
                # (the scheduler's columnar confirm) sees the whole wave
                self._deliver(h.on_batch, frame, applied)
                continue
            for etype, old, obj, _i in applied:
                if etype == ADDED:
                    self._deliver(h.on_add, obj)
                elif etype == MODIFIED:
                    self._deliver(h.on_update, old, obj)
                elif etype == DELETED:
                    self._deliver(h.on_delete, old if old is not None else obj)
        dt = time.perf_counter() - t_apply
        with self._mu:
            self.stats["apply_s"] += dt
        sp.set(applied=len(applied), dropped=dropped,
               decode_errors=decode_errors, decode_s=round(decode_s, 6))

    # -- cache compaction (promote-and-drop-raw) ---------------------------
    def compact_cache(self) -> int:
        """Opt-in sweep over a synced cache: promote every lazy view to
        its typed form and release the pinned wire dict (carried-forward
        ROADMAP item — a cached lazy object otherwise keeps its raw
        payload alive for its lifetime).  Promotion is exactly what any
        reader would have triggered, so concurrent readers are safe; the
        objects' observable value is unchanged (promotion ≡ from_dict).
        Returns the number of objects whose raw payload was dropped.

        Observability (ISSUE 7 satellite): each sweep counts the objects
        it compacted (``client_informer_compactions_total``) and records
        the approximate wire bytes it released
        (``client_informer_compaction_freed_bytes``)."""
        with self._mu:
            objs = list(self._cache.values())
        n = 0
        freed = 0
        for obj in objs:
            try:
                size = lazy_mod.raw_payload_size(obj)
                if lazy_mod.promote_and_drop_raw(obj):
                    n += 1
                    freed += size
            except Exception:  # noqa: BLE001 - sweep is best-effort
                logger.exception("informer %s: compaction failed for one "
                                 "object (kept as-is)", self.kind)
        with self._mu:
            self.stats["compactions"] += n
        if n:
            self.metrics.informer_compactions.inc(n)
        self.metrics.informer_compaction_freed_bytes.set(freed)
        return n


class CacheMutationError(RuntimeError):
    pass


class InformerFactory:
    """SharedInformerFactory analogue: one informer per kind per factory."""

    def __init__(self, clientset, mutation_detector: bool = False,
                 compact_on_resync: bool = False):
        self._clientset = clientset
        self._informers: dict[str, SharedInformer] = {}
        self._mutation_detector = mutation_detector
        self._compact_on_resync = compact_on_resync
        # informer() is reachable from controller sync workers (the GC
        # wiring a just-established CRD kind mid-sync): without the lock
        # two workers can build two informers for one kind and the
        # loser's handlers are silently dropped
        self._mk_mu = threading.Lock()

    def informer(self, kind: str) -> SharedInformer:
        inf = self._informers.get(kind)  # hit path: lock-free
        if inf is None:
            with self._mk_mu:
                inf = self._informers.get(kind)
                if inf is None:
                    inf = SharedInformer(
                        self._clientset.client_for(kind),
                        mutation_detector=self._mutation_detector,
                        compact_on_resync=self._compact_on_resync,
                    )
                    self._informers[kind] = inf
        return inf

    def start_all(self) -> None:
        for inf in self._informers.values():
            if not inf.has_synced():
                inf.start()

    def start_all_manual(self) -> None:
        for inf in self._informers.values():
            if not inf.has_synced():
                inf.start_manual()

    def pump_all(self) -> int:
        # snapshot: a handler may register a NEW informer mid-pump (the
        # GC wiring a just-established CRD kind); the newcomer gets its
        # events on the caller's next pump round
        return sum(inf.pump() for inf in list(self._informers.values()))

    def relist_all(self) -> None:
        """Resync every synced informer (the factory-level resyncPeriod
        tick): each one re-LISTs, diffs, and restarts its watch."""
        for inf in list(self._informers.values()):
            if inf.has_synced():
                inf.relist()

    def compact_all(self) -> int:
        """Promote-and-drop-raw sweep over every synced cache (opt-in:
        trades decode-now for releasing the pinned wire payloads)."""
        return sum(inf.compact_cache()
                   for inf in list(self._informers.values())
                   if inf.has_synced())

    def stop_all(self) -> None:
        for inf in self._informers.values():
            inf.stop()


class PodNodeIndex:
    """By-node pod index over a shared informer (fieldSelector analogue).

    Mutated on the informer's run-loop thread, read from controller worker
    threads (``pods_on``) — both sides hold ``_mu`` (ktpu-analyze RL303)."""

    def __init__(self, informer: "SharedInformer"):
        self._mu = threading.Lock()
        self._by_node: dict[str, dict[str, "api.Pod"]] = {}
        informer.add_handler(
            Handler(on_add=self._upsert, on_update=lambda old, new: self._move(old, new),
                    on_delete=self._drop)
        )

    def _upsert(self, pod: "api.Pod") -> None:
        if pod.spec.node_name:
            with self._mu:
                self._by_node.setdefault(pod.spec.node_name, {})[pod.meta.key] = pod

    def _move(self, old: Optional["api.Pod"], new: "api.Pod") -> None:
        # pop + insert under ONE lock hold: releasing between them leaves a
        # window where the pod is indexed on no node and a concurrent
        # pods_on() reader misses it entirely
        with self._mu:
            if old is not None and old.spec.node_name and old.spec.node_name != new.spec.node_name:
                self._by_node.get(old.spec.node_name, {}).pop(old.meta.key, None)
                self._shed(old.spec.node_name)
            if new.spec.node_name:
                self._by_node.setdefault(new.spec.node_name, {})[new.meta.key] = new

    def _drop(self, pod: "api.Pod") -> None:
        if pod.spec.node_name:
            with self._mu:
                self._by_node.get(pod.spec.node_name, {}).pop(pod.meta.key, None)
                self._shed(pod.spec.node_name)

    def _shed(self, node_name: str) -> None:
        # caller holds _mu: drop the per-node dict once its last pod is
        # gone, or node churn (scale-down, spot reclaim) pins an empty
        # dict per node name the cluster has ever seen
        if not self._by_node.get(node_name):
            self._by_node.pop(node_name, None)

    def pods_on(self, node_name: str) -> list:
        with self._mu:
            return list(self._by_node.get(node_name, {}).values())


class PodOwnerIndex:
    """Pods indexed by controller-owner UID, plus orphans by namespace — the
    index that makes ReplicaSet reconciliation O(pods-of-this-RS) instead of
    O(cluster-pods) (client-go keeps the same index inside its Indexer)."""

    def __init__(self, informer: "SharedInformer"):
        # informer-thread writers vs worker-thread readers (RL303)
        self._mu = threading.Lock()
        self._by_owner: dict[str, dict[str, object]] = {}
        self._orphans: dict[str, dict[str, object]] = {}  # namespace -> key -> pod
        informer.add_handler(
            Handler(
                on_add=self._upsert,
                on_update=lambda old, new: self._move(old, new),
                on_delete=self._drop,
            )
        )

    def _slot(self, pod):
        # caller holds _mu
        ref = pod.meta.controller_ref()
        if ref is not None:
            return self._by_owner.setdefault(ref.uid, {})
        return self._orphans.setdefault(pod.meta.namespace, {})

    def _upsert(self, pod) -> None:
        with self._mu:
            self._slot(pod)[pod.meta.key] = pod

    def _move(self, old, new) -> None:
        with self._mu:
            if old is not None:
                self._slot(old).pop(old.meta.key, None)
                self._shed(old)
            self._slot(new)[new.meta.key] = new

    def _drop(self, pod) -> None:
        with self._mu:
            self._slot(pod).pop(pod.meta.key, None)
            self._shed(pod)

    def _shed(self, pod) -> None:
        # caller holds _mu: drop the slot itself once its last pod is
        # gone, or dead owner UIDs and emptied namespaces pin an empty
        # dict forever (every RS the cluster has ever run)
        ref = pod.meta.controller_ref()
        if ref is not None:
            if not self._by_owner.get(ref.uid):
                self._by_owner.pop(ref.uid, None)
        elif not self._orphans.get(pod.meta.namespace):
            self._orphans.pop(pod.meta.namespace, None)

    def owned_by(self, uid: str) -> list:
        with self._mu:
            return list(self._by_owner.get(uid, {}).values())

    def orphans_in(self, namespace: str) -> list:
        with self._mu:
            return list(self._orphans.get(namespace, {}).values())
