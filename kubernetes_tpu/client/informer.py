"""Shared informer: LIST+WATCH → local indexed cache → handler fan-out.

The reference's list-watch-cache stack
(``client-go/tools/cache``: ``reflector.go:239 ListAndWatch``,
``shared_informer.go:182 Run`` + ``processorListener :537``) collapsed into
one component: list to seed the cache at a revision, watch from that
revision, apply deltas to an indexed local store, and fan events out to any
number of handlers (SURVEY.md P4).

Two drive modes:

- ``start()`` — background thread, production-shaped;
- ``pump()`` — synchronously drain pending watch events on the caller's
  thread.  Deterministic tests and single-threaded control loops use this;
  it is the informer analogue of running the event loop manually.

Objects handed to handlers are shared and MUST NOT be mutated.  With
``mutation_detector=True`` the informer snapshots each object and panics on
divergence — the reference's ``KUBE_CACHE_MUTATION_DETECTOR``
(``tools/cache/mutation_detector.go``).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..api import types as api
from ..store.store import ADDED, DELETED, MODIFIED, ExpiredRevisionError, WatchEvent
from .clientset import TypedClient


class Handler:
    def __init__(
        self,
        on_add: Optional[Callable] = None,
        on_update: Optional[Callable] = None,
        on_delete: Optional[Callable] = None,
    ):
        self.on_add = on_add or (lambda obj: None)
        self.on_update = on_update or (lambda old, new: None)
        self.on_delete = on_delete or (lambda obj: None)


class SharedInformer:
    def __init__(self, client: TypedClient, mutation_detector: bool = False):
        self._client = client
        self.kind = client.kind
        self._handlers: list[Handler] = []
        self._cache: dict[str, object] = {}  # key -> typed object
        self._mu = threading.RLock()
        self._synced = threading.Event()
        self._watch = None
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._mutation_detector = mutation_detector
        self._snapshots: dict[str, dict] = {}
        self.last_revision = 0

    # -- registration ------------------------------------------------------
    def add_handler(self, handler: Handler) -> None:
        with self._mu:
            self._handlers.append(handler)
            if self._synced.is_set():
                for obj in list(self._cache.values()):
                    handler.on_add(obj)

    # -- cache reads (the Lister/Indexer surface) --------------------------
    def get(self, key: str):
        with self._mu:
            return self._cache.get(key)

    def list(self) -> list:
        with self._mu:
            return list(self._cache.values())

    def keys(self) -> list[str]:
        with self._mu:
            return list(self._cache.keys())

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- lifecycle ---------------------------------------------------------
    def _seed(self) -> None:
        objs, rev = self._client.list()
        with self._mu:
            self._cache = {o.meta.key: o for o in objs}
            if self._mutation_detector:
                self._snapshots = {o.meta.key: o.to_dict() for o in objs}
            self.last_revision = rev
            self._watch = self._client.watch(from_revision=rev)
            handlers = list(self._handlers)
            objs_now = list(self._cache.values())
        for h in handlers:
            for o in objs_now:
                h.on_add(o)
        self._synced.set()

    def start(self) -> None:
        """Seed synchronously, then consume the watch on a daemon thread."""
        self._seed()
        self._thread = threading.Thread(target=self._run_loop, daemon=True)
        self._thread.start()

    def start_manual(self) -> None:
        """Seed synchronously; caller drives with pump()."""
        self._seed()

    def stop(self) -> None:
        self._stopped.set()
        if self._watch is not None:
            self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run_loop(self) -> None:
        while not self._stopped.is_set():
            ev = self._watch.get(timeout=0.2)
            if ev is None:
                continue
            self._apply(ev)

    def pump(self, max_events: Optional[int] = None) -> int:
        """Synchronously apply all (or up to max_events) pending events.
        A no-op when the watch thread owns the stream (mixed drivers —
        e.g. a clock tick inside a threaded daemon — must not compete
        for events)."""
        if self._thread is not None:
            return 0
        if self._watch is None:
            self._seed()
        n = 0
        while max_events is None or n < max_events:
            ev = self._watch.get(timeout=0)
            if ev is None:
                break
            self._apply(ev)
            n += 1
        return n

    # -- delta application -------------------------------------------------
    def _apply(self, ev: WatchEvent) -> None:
        obj = self._client._cls.from_dict(ev.object)
        with self._mu:
            old = self._cache.get(ev.key)
            if self._mutation_detector and old is not None:
                snap = self._snapshots.get(ev.key)
                if snap is not None and old.to_dict() != snap:
                    raise CacheMutationError(
                        f"{self.kind} {ev.key} was mutated in the informer cache"
                    )
            if ev.type == DELETED:
                self._cache.pop(ev.key, None)
                self._snapshots.pop(ev.key, None)
            else:
                self._cache[ev.key] = obj
                if self._mutation_detector:
                    self._snapshots[ev.key] = obj.to_dict()
            self.last_revision = max(self.last_revision, ev.revision)
            handlers = list(self._handlers)
        for h in handlers:
            if ev.type == ADDED:
                h.on_add(obj)
            elif ev.type == MODIFIED:
                h.on_update(old, obj)
            elif ev.type == DELETED:
                h.on_delete(old if old is not None else obj)


class CacheMutationError(RuntimeError):
    pass


class InformerFactory:
    """SharedInformerFactory analogue: one informer per kind per factory."""

    def __init__(self, clientset, mutation_detector: bool = False):
        self._clientset = clientset
        self._informers: dict[str, SharedInformer] = {}
        self._mutation_detector = mutation_detector

    def informer(self, kind: str) -> SharedInformer:
        if kind not in self._informers:
            self._informers[kind] = SharedInformer(
                self._clientset.client_for(kind), mutation_detector=self._mutation_detector
            )
        return self._informers[kind]

    def start_all(self) -> None:
        for inf in self._informers.values():
            if not inf.has_synced():
                inf.start()

    def start_all_manual(self) -> None:
        for inf in self._informers.values():
            if not inf.has_synced():
                inf.start_manual()

    def pump_all(self) -> int:
        # snapshot: a handler may register a NEW informer mid-pump (the
        # GC wiring a just-established CRD kind); the newcomer gets its
        # events on the caller's next pump round
        return sum(inf.pump() for inf in list(self._informers.values()))

    def stop_all(self) -> None:
        for inf in self._informers.values():
            inf.stop()


class PodNodeIndex:
    """By-node pod index over a shared informer (fieldSelector analogue).

    Mutated on the informer's run-loop thread, read from controller worker
    threads (``pods_on``) — both sides hold ``_mu`` (ktpu-analyze RL303)."""

    def __init__(self, informer: "SharedInformer"):
        self._mu = threading.Lock()
        self._by_node: dict[str, dict[str, "api.Pod"]] = {}
        informer.add_handler(
            Handler(on_add=self._upsert, on_update=lambda old, new: self._move(old, new),
                    on_delete=self._drop)
        )

    def _upsert(self, pod: "api.Pod") -> None:
        if pod.spec.node_name:
            with self._mu:
                self._by_node.setdefault(pod.spec.node_name, {})[pod.meta.key] = pod

    def _move(self, old: Optional["api.Pod"], new: "api.Pod") -> None:
        # pop + insert under ONE lock hold: releasing between them leaves a
        # window where the pod is indexed on no node and a concurrent
        # pods_on() reader misses it entirely
        with self._mu:
            if old is not None and old.spec.node_name and old.spec.node_name != new.spec.node_name:
                self._by_node.get(old.spec.node_name, {}).pop(old.meta.key, None)
            if new.spec.node_name:
                self._by_node.setdefault(new.spec.node_name, {})[new.meta.key] = new

    def _drop(self, pod: "api.Pod") -> None:
        if pod.spec.node_name:
            with self._mu:
                self._by_node.get(pod.spec.node_name, {}).pop(pod.meta.key, None)

    def pods_on(self, node_name: str) -> list:
        with self._mu:
            return list(self._by_node.get(node_name, {}).values())


class PodOwnerIndex:
    """Pods indexed by controller-owner UID, plus orphans by namespace — the
    index that makes ReplicaSet reconciliation O(pods-of-this-RS) instead of
    O(cluster-pods) (client-go keeps the same index inside its Indexer)."""

    def __init__(self, informer: "SharedInformer"):
        # informer-thread writers vs worker-thread readers (RL303)
        self._mu = threading.Lock()
        self._by_owner: dict[str, dict[str, object]] = {}
        self._orphans: dict[str, dict[str, object]] = {}  # namespace -> key -> pod
        informer.add_handler(
            Handler(
                on_add=self._upsert,
                on_update=lambda old, new: self._move(old, new),
                on_delete=self._drop,
            )
        )

    def _slot(self, pod):
        # caller holds _mu
        ref = pod.meta.controller_ref()
        if ref is not None:
            return self._by_owner.setdefault(ref.uid, {})
        return self._orphans.setdefault(pod.meta.namespace, {})

    def _upsert(self, pod) -> None:
        with self._mu:
            self._slot(pod)[pod.meta.key] = pod

    def _move(self, old, new) -> None:
        with self._mu:
            if old is not None:
                self._slot(old).pop(old.meta.key, None)
            self._slot(new)[new.meta.key] = new

    def _drop(self, pod) -> None:
        with self._mu:
            self._slot(pod).pop(pod.meta.key, None)

    def owned_by(self, uid: str) -> list:
        with self._mu:
            return list(self._by_owner.get(uid, {}).values())

    def orphans_in(self, namespace: str) -> list:
        with self._mu:
            return list(self._orphans.get(namespace, {}).values())
