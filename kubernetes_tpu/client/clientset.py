"""Typed client layer over the store.

The capability of the reference's generated clientsets
(``staging/src/k8s.io/client-go/kubernetes``): typed create/get/list/
update/delete/watch per kind, plus the two special verbs the control plane
runs on:

- ``PodClient.bind`` — the Binding subresource
  (``pkg/registry/core/pod/storage/storage.go:128 BindingREST``): the ONLY
  way a placement is committed; a CAS update that sets ``spec.nodeName``
  and fails if the pod is already bound to a different node.
- ``update_status`` — status subresource semantics (spec untouched).

In-process today (function calls instead of HTTPS+protobuf), but the
interface is transport-shaped: everything passes through serialization, so
a wire transport can be slotted under ``Clientset`` without touching
callers.
"""

from __future__ import annotations

import copy
from typing import Callable, Optional, Type

from ..api import lazy as lazy_mod
from ..api import types as api
from ..store.store import Store, Watch


# Kinds whose objects live outside any namespace (reference: node is
# cluster-scoped; its store key is the bare name).  Populated by the type
# registry (api.types.register_kind).
from ..api.types import CLUSTER_SCOPED_KINDS  # noqa: E402


class TypedClient:
    def __init__(self, store: Store, kind: str, cls: Type):
        self._store = store
        self.kind = kind
        self._cls = cls
        self.default_namespace = "" if kind in CLUSTER_SCOPED_KINDS else "default"
        import inspect

        def _takes_trusted(fn) -> bool:
            if fn is None:
                return False
            try:
                return "_trusted" in inspect.signature(fn).parameters
            except (TypeError, ValueError):
                return False

        self._trusted_create = _takes_trusted(store.create)
        self._trusted_create_many = _takes_trusted(
            getattr(store, "create_many", None))

        def _takes_frames(fn) -> bool:
            try:
                return "frames" in inspect.signature(fn).parameters
            except (TypeError, ValueError):
                return False

        # column-packed watch delivery (store/frames.py): opt-in per
        # watcher, and only when the transport speaks it — a pre-frame
        # store silently degrades to per-event delivery
        self._watch_frames = _takes_frames(store.watch)

    def _ns(self, namespace: Optional[str]) -> str:
        """Resolve the effective namespace.  Cluster-scoped kinds ignore any
        caller/object namespace (reference: the registry's scope strategy,
        not the caller, decides key shape) — otherwise an ObjectMeta
        carrying the "default" namespace stores the object where
        cluster-scoped get/update can never find it."""
        if self.default_namespace == "":
            return ""
        return self.default_namespace if namespace is None else namespace

    def _to_wire(self, obj) -> dict:
        d = obj.to_dict()
        meta = d.setdefault("metadata", {})
        meta["namespace"] = self._ns(meta.get("namespace"))
        return d

    def _decode(self, d: dict):
        """Decode a store response: a lazy view on the zero-copy path
        (callers that never read the result — fire-and-forget creates,
        heartbeat updates — pay nothing; readers promote on touch), the
        eager typed decode on the compatibility path."""
        if lazy_mod.ENABLED:
            return lazy_mod.lazy_class(self._cls)(d)
        return self._cls.from_dict(d)

    def _create_raw(self, obj) -> dict:
        """One store create over the freshly built wire dict.  Stores
        whose create accepts ``_trusted`` (the in-process one) take it
        without a defensive deep copy — ``to_dict`` output is private by
        construction; other transports get the plain call."""
        if self._trusted_create:
            return self._store.create(self.kind, self._to_wire(obj),
                                      _trusted=True)
        return self._store.create(self.kind, self._to_wire(obj))

    def create(self, obj):
        return self._decode(self._create_raw(obj))

    def create_nowait(self, obj) -> None:
        """``create`` without decoding the stored object back — for
        fire-and-forget writers (the event sink) where the return decode
        is pure overhead on a contended thread."""
        self._create_raw(obj)

    def _create_many_raw(self, objs) -> list:
        """Batch create through the store's one-txn path when the
        transport offers it (``Store.create_many``: one lock/WAL/fanout
        pass for the whole list), else a per-object loop with identical
        semantics.  Items that fail (already exists) come back as None;
        the rest commit — the best-effort contract batch writers want."""
        wires = [self._to_wire(o) for o in objs]
        fn = getattr(self._store, "create_many", None)
        if fn is not None:
            if self._trusted_create_many:
                return fn(self.kind, wires, _trusted=True)
            return fn(self.kind, wires)
        out = []
        for w in wires:
            try:
                out.append(self._store.create(self.kind, w))
            except Exception:  # noqa: BLE001 - per-item best effort
                out.append(None)
        return out

    def create_many(self, objs) -> list:
        """Batch create; one decoded object (or None) per input, in order."""
        return [self._decode(d) if d is not None else None
                for d in self._create_many_raw(objs)]

    def create_many_nowait(self, objs) -> None:
        """Batch create for fire-and-forget writers (the event sink's
        whole drained chunk, a bench wave's arrivals): no return decode."""
        self._create_many_raw(objs)

    def get(self, name: str, namespace: Optional[str] = None):
        return self._decode(self._store.get(self.kind, self._ns(namespace), name))

    def list(self, namespace: Optional[str] = None):
        if namespace is not None:
            namespace = self._ns(namespace)
        dicts, rev = self._store.list(self.kind, namespace)
        return [self._cls.from_dict(d) for d in dicts], rev

    def list_lazy(self, namespace: Optional[str] = None):
        """LIST into decode-on-access views (``api/lazy.py``): same
        objects semantically, but ``from_dict`` is deferred until a field
        is actually read — the informer seed path's zero-copy arm."""
        if namespace is not None:
            namespace = self._ns(namespace)
        dicts, rev = self._store.list(self.kind, namespace)
        cls = lazy_mod.lazy_class(self._cls)
        return [cls(d) for d in dicts], rev

    def list_columns(self):
        """Packed column batch for kinds with a columnar emitter (Pod),
        when the transport supports it; None otherwise (callers fall
        back to :meth:`list_lazy`/:meth:`list`)."""
        fn = getattr(self._store, "list_columns", None)
        if fn is None:
            return None
        return fn(self.kind)

    def update(self, obj):
        return self._decode(self._store.update(self.kind, self._to_wire(obj)))

    def guaranteed_update(self, name: str, mutate: Callable, namespace: Optional[str] = None):
        """mutate receives a typed object, returns the new typed object."""
        namespace = self._ns(namespace)

        def _mutate_dict(d: dict) -> dict:
            return mutate(self._cls.from_dict(d)).to_dict()

        return self._cls.from_dict(
            self._store.guaranteed_update(self.kind, namespace, name, _mutate_dict)
        )

    def update_status(self, obj):
        """Write only .status (+ heartbeat metadata), preserving concurrent
        spec/label changes, like the /status subresource."""
        status = obj.to_dict().get("status")

        def _mutate(cur):
            d = cur.to_dict()
            d["status"] = copy.deepcopy(status)
            return self._cls.from_dict(d)

        return self.guaranteed_update(obj.meta.name, _mutate, obj.meta.namespace)

    def delete(self, name: str, namespace: Optional[str] = None):
        return self._cls.from_dict(self._store.delete(self.kind, self._ns(namespace), name))

    def watch(self, from_revision: Optional[int] = None,
              frames: bool = False) -> Watch:
        """``frames=True`` requests column-packed batch delivery (one
        WatchFrame per correlated store txn) when the transport supports
        it; per-event otherwise.  Only frame-aware consumers (the
        informer's batch apply) should opt in."""
        if frames and self._watch_frames:
            return self._store.watch(self.kind, from_revision, frames=True)
        return self._store.watch(self.kind, from_revision)


class PodClient(TypedClient):
    def __init__(self, store: Store):
        super().__init__(store, "Pod", api.Pod)

    def bind(self, binding: api.Binding) -> None:
        """Commit a placement (BindingREST.Create → assignPod →
        setPodHostAndAnnotations, ``storage.go:141,157,191``).

        Operates at the wire-dict level — no typed round-trip.  This is the
        scheduler's hottest write (one per scheduled pod; the batch path
        issues hundreds of thousands), so it must stay O(small-dict-copy)."""

        def _assign(d: dict) -> dict:
            cur = (d.get("spec") or {}).get("nodeName", "")
            if cur and cur != binding.node_name:
                raise BindConflictError(
                    f"pod {binding.pod_namespace}/{binding.pod_name} already bound to {cur}"
                )
            d.setdefault("spec", {})["nodeName"] = binding.node_name
            return d

        self._store.guaranteed_update(
            "Pod", binding.pod_namespace, binding.pod_name, _assign
        )

    def bind_many(self, bindings: list[api.Binding]) -> list[Optional[str]]:
        """Batch placement commit (one store txn); per-item error or None."""
        return self._store.bind_many(
            [(b.pod_namespace, b.pod_name, b.node_name) for b in bindings]
        )

    def evict(self, name: str, namespace: Optional[str] = None) -> None:
        """PDB-aware voluntary eviction — the ``pods/eviction`` subresource
        (reference ``pkg/registry/core/pod/rest/eviction.go``): every PDB
        selecting the pod must have ``disruptionsAllowed > 0``; the budget
        is CAS-decremented before the delete so racing evictions cannot
        overdraw it (the disruption controller replenishes)."""
        from ..api.selectors import LabelSelector
        from ..store.store import ConflictError

        if namespace is None:
            namespace = self.default_namespace
        pod = self.get(name, namespace)
        pdbs, _ = self._store.list("PodDisruptionBudget", namespace)
        charged: list[str] = []
        try:
            for pdb in pdbs:
                sel = LabelSelector.from_dict((pdb.get("spec") or {}).get("selector"))
                if not sel.matches(pod.meta.labels):
                    continue
                pdb_name = pdb["metadata"]["name"]

                def _decrement(cur: dict) -> dict:
                    status = cur.setdefault("status", {})
                    allowed = int(status.get("disruptionsAllowed", 0))
                    if allowed <= 0:
                        raise EvictionDisallowed(
                            f"cannot evict {namespace}/{name}: PDB {pdb_name} "
                            "allows no disruptions"
                        )
                    status["disruptionsAllowed"] = allowed - 1
                    return cur

                self._store.guaranteed_update(
                    "PodDisruptionBudget", namespace, pdb_name, _decrement
                )
                charged.append(pdb_name)
            self.delete(name, namespace)
        except Exception:
            # roll the budget back for any PDB already charged
            for pdb_name in charged:
                def _refund(cur: dict) -> dict:
                    status = cur.setdefault("status", {})
                    status["disruptionsAllowed"] = int(status.get("disruptionsAllowed", 0)) + 1
                    return cur

                try:
                    self._store.guaranteed_update(
                        "PodDisruptionBudget", namespace, pdb_name, _refund
                    )
                except KeyError:
                    pass
            raise


class BindConflictError(Exception):
    pass


class EvictionDisallowed(Exception):
    """Eviction refused by a PodDisruptionBudget (HTTP 429 in the
    reference's eviction subresource)."""


class Clientset:
    """One handle per registered kind (``clientset.Interface`` analogue),
    exposed under the kind's plural resource name (``cs.pods``,
    ``cs.daemonsets``, …).  Kinds registered later (e.g. CRDs) are
    reachable via ``client_for``."""

    def __init__(self, store: Store):
        self.store = store
        self.pods = PodClient(store)
        self._by_kind: dict[str, TypedClient] = {"Pod": self.pods}
        for kind, cls in api.KINDS.items():
            if kind == "Pod":
                continue
            client = TypedClient(store, kind, cls)
            self._by_kind[kind] = client
            setattr(self, api.KIND_PLURALS[kind], client)

    def client_for(self, kind: str) -> TypedClient:
        if kind not in self._by_kind:
            # kind registered after construction (CRD): build on demand
            cls = api.KINDS.get(kind)
            if cls is None:
                raise KeyError(kind)
            self._by_kind[kind] = TypedClient(self.store, kind, cls)
        return self._by_kind[kind]
