"""In-cluster DNS: the kube-dns addon analogue (``cluster/addons/dns/``)."""

from .records import DEFAULT_ZONE, DNSRecordStore
from .server import DNSServer, lookup

__all__ = ["DEFAULT_ZONE", "DNSRecordStore", "DNSServer", "lookup"]
