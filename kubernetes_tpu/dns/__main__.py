"""The kube-dns daemon: ``python -m kubernetes_tpu.dns --apiserver URL``.

Watches Services/Endpoints over the wire and serves the cluster zone on a
UDP port (reference: the kube-dns addon pod, ``cluster/addons/dns/``)."""

from __future__ import annotations

import argparse
import logging
import time


def main() -> None:
    parser = argparse.ArgumentParser(prog="kube-dns")
    parser.add_argument("--apiserver", default=None)
    parser.add_argument("--token", default=None)
    parser.add_argument("--kubeconfig", default=None)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=10053)
    parser.add_argument("--zone", default="cluster.local")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    from .records import DNSRecordStore
    from .server import DNSServer

    from ..daemon import remote_clientset

    if not args.apiserver and not args.kubeconfig:
        parser.error("one of --apiserver or --kubeconfig is required")
    cs = remote_clientset(args.apiserver, args.token,
                          kubeconfig=args.kubeconfig)
    records = DNSRecordStore(cs, zone=args.zone)
    records.start(manual=False)  # threaded informer watch loops
    server = DNSServer(records, host=args.host, port=args.port)
    server.start()
    logging.info("kube-dns serving zone %s on %s:%d", args.zone,
                 *server.address)
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
