"""In-cluster DNS records: Services/Endpoints/Pods → name table.

Capability of the kube-dns addon (reference ``cluster/addons/dns/``,
skydns backed by the kubernetes "treecache" source): watch Services and
Endpoints and materialize the cluster DNS schema

- ``<svc>.<ns>.svc.<zone>``            A → clusterIP (ClusterIP services)
- ``<svc>.<ns>.svc.<zone>``            A → every ready backend IP
                                       (headless services, clusterIP: None)
- ``<pod>.<svc>.<ns>.svc.<zone>``      A → that backend pod's IP (headless
                                       per-pod records, StatefulSet identity)
- ``_<port>._<proto>.<svc>.<ns>.svc.<zone>``  SRV → (port, <svc>.<ns>.svc)
- ``<a-b-c-d>.<ns>.pod.<zone>``        A → a.b.c.d (pod IP echo records)

The table is informer-driven (LIST+WATCH, not polling) and rebuilt
per-service on each event — the treecache analogue, sized for hollow
clusters.  ``resolve()`` is the in-process query API; ``dns.server``
speaks the real wire protocol over UDP on top of it.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..api import types as api
from ..client.informer import Handler, InformerFactory

DEFAULT_ZONE = "cluster.local"


class DNSRecordStore:
    """svc/endpoints → A + SRV record table for one cluster zone."""

    def __init__(self, clientset, informers: Optional[InformerFactory] = None,
                 zone: str = DEFAULT_ZONE):
        self.clientset = clientset
        self.zone = zone.strip(".")
        self.informers = informers or InformerFactory(clientset)
        self._mu = threading.Lock()
        # per-service shards so one service's churn doesn't rebuild the world
        self._a_by_svc: dict[str, dict[str, list[str]]] = {}
        self._srv_by_svc: dict[str, dict[str, list[tuple[int, str]]]] = {}
        self._wire()

    # -- informer wiring ----------------------------------------------------
    def _wire(self) -> None:
        svcs = self.informers.informer("Service")
        svcs.add_handler(Handler(
            on_add=lambda s: self._sync_service(s.meta.key),
            on_update=lambda old, new: self._sync_service(new.meta.key),
            on_delete=lambda s: self._drop_service(s.meta.key),
        ))
        eps = self.informers.informer("Endpoints")
        eps.add_handler(Handler(
            on_add=lambda e: self._sync_service(e.meta.key),
            on_update=lambda old, new: self._sync_service(new.meta.key),
            on_delete=lambda e: self._sync_service(e.meta.key),
        ))

    def start(self, manual: bool = True) -> None:
        if manual:
            self.informers.start_all_manual()
        else:
            self.informers.start_all()
        self.resync()

    def pump(self) -> int:
        return self.informers.pump_all()

    def resync(self) -> None:
        for svc in self.informers.informer("Service").list():
            self._sync_service(svc.meta.key)

    # -- record building ----------------------------------------------------
    def _drop_service(self, key: str) -> None:
        with self._mu:
            self._a_by_svc.pop(key, None)
            self._srv_by_svc.pop(key, None)

    def _sync_service(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        svc = self.informers.informer("Service").get(key)
        if svc is None:
            self._drop_service(key)
            return
        base = f"{name}.{namespace}.svc.{self.zone}"
        a: dict[str, list[str]] = {}
        srv: dict[str, list[tuple[int, str]]] = {}
        eps = self.informers.informer("Endpoints").get(key)
        headless = svc.cluster_ip in ("", "None")
        if not headless:
            a[base] = [svc.cluster_ip]
        backend_ips: list[str] = []
        pod_targets: list[str] = []
        unnamed_backend = False  # ready address with no backing-pod name
        if eps is not None:
            for subset in eps.subsets:
                for addr in subset.addresses:
                    if not addr.ip:
                        continue
                    backend_ips.append(addr.ip)
                    if not addr.target_pod:
                        unnamed_backend = True
                    # per-pod record: <pod>.<svc>.<ns>.svc.<zone> (the
                    # StatefulSet stable-identity path; hostname = the
                    # backing pod's name)
                    if addr.target_pod:
                        pod_name = addr.target_pod.rsplit("/", 1)[-1]
                        a.setdefault(f"{pod_name}.{base}", []).append(addr.ip)
                        pod_targets.append(f"{pod_name}.{base}")
        if headless and backend_ips:
            a[base] = sorted(set(backend_ips))
        # SRV: _<portname>._<proto>.<base> -> (port, target). ClusterIP
        # services target the service name; headless services answer one
        # SRV tuple per ready backend targeting the per-pod name (the
        # reference skydns returns per-backend-pod SRV targets).
        for port in svc.ports:
            if not port.name:
                continue
            sname = f"_{port.name}._{port.protocol.lower()}.{base}"
            if headless and pod_targets:
                for tgt in sorted(set(pod_targets)):
                    srv.setdefault(sname, []).append((port.port, tgt))
                if unnamed_backend:
                    # manually-added (pod-less) backends stay reachable
                    # through the base target, whose A record lists them
                    srv[sname].append((port.port, base))
            else:
                srv.setdefault(sname, []).append((port.port, base))
        with self._mu:
            self._a_by_svc[key] = a
            self._srv_by_svc[key] = srv

    # -- queries -------------------------------------------------------------
    def _pod_echo(self, qname: str) -> Optional[list[str]]:
        """<a-b-c-d>.<ns>.pod.<zone> → a.b.c.d (no state needed)."""
        suffix = f".pod.{self.zone}"
        if not qname.endswith(suffix):
            return None
        head = qname[: -len(suffix)]
        parts = head.split(".")
        if len(parts) != 2:
            return None
        octets = parts[0].split("-")
        if len(octets) != 4 or not all(o.isdigit() and int(o) < 256 for o in octets):
            return None
        return [".".join(octets)]

    def resolve(self, qname: str, qtype: str = "A"):
        """A → list of IPs; SRV → list of (port, target). Empty on miss."""
        qname = qname.strip(".").lower()
        if qtype == "A":
            echo = self._pod_echo(qname)
            if echo is not None:
                return echo
            with self._mu:
                for recs in self._a_by_svc.values():
                    if qname in recs:
                        return list(recs[qname])
            return []
        if qtype == "SRV":
            with self._mu:
                for recs in self._srv_by_svc.values():
                    if qname in recs:
                        return list(recs[qname])
            return []
        return []

    def all_names(self) -> list[str]:
        with self._mu:
            names = set()
            for recs in self._a_by_svc.values():
                names.update(recs)
            for recs in self._srv_by_svc.values():
                names.update(recs)
        return sorted(names)
