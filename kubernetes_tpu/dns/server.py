"""A real DNS server over UDP for the cluster zone.

The skydns half of the kube-dns addon (reference ``cluster/addons/dns/``):
real RFC-1035 wire format — header, QNAME label encoding, A and SRV
answers, NXDOMAIN/NOERROR codes — served from ``DNSRecordStore`` over a
datagram socket.  Pods (hollow or real processes) point their resolver at
this address; `svc.ns.svc.cluster.local` resolution happens over actual
UDP bytes, mirroring how the userspace proxier moves real TCP bytes.

Only the query opcode and IN class are implemented — the subset kube-dns
actually serves.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from .records import DNSRecordStore

QTYPE_A = 1
QTYPE_SRV = 33
QTYPE_ANY = 255
QCLASS_IN = 1

RCODE_OK = 0
RCODE_NXDOMAIN = 3
RCODE_NOTIMPL = 4


def encode_name(name: str) -> bytes:
    out = b""
    for label in name.strip(".").split("."):
        raw = label.encode()
        out += struct.pack("!B", len(raw)) + raw
    return out + b"\x00"


def decode_name(buf: bytes, off: int) -> tuple[str, int]:
    """Decode a (possibly compressed) QNAME; returns (name, next offset)."""
    labels = []
    jumps = 0
    end = None
    while True:
        if off >= len(buf):
            raise ValueError("truncated name")
        length = buf[off]
        if length & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(buf):
                raise ValueError("truncated pointer")
            ptr = ((length & 0x3F) << 8) | buf[off + 1]
            if end is None:
                end = off + 2
            off = ptr
            jumps += 1
            if jumps > 16:
                raise ValueError("pointer loop")
            continue
        off += 1
        if length == 0:
            break
        labels.append(buf[off:off + length].decode(errors="replace"))
        off += length
    return ".".join(labels), (end if end is not None else off)


def build_query(qname: str, qtype: int, txid: int = 0x1234) -> bytes:
    header = struct.pack("!HHHHHH", txid, 0x0100, 1, 0, 0, 0)  # RD set
    return header + encode_name(qname) + struct.pack("!HH", qtype, QCLASS_IN)


def parse_response(buf: bytes):
    """Minimal answer parser (tests / in-cluster resolver client).
    Returns (rcode, [(name, qtype, rdata)]) where rdata is an IP string
    for A and (priority, weight, port, target) for SRV."""
    (txid, flags, qd, an, ns, ar) = struct.unpack("!HHHHHH", buf[:12])
    rcode = flags & 0xF
    off = 12
    for _ in range(qd):
        _, off = decode_name(buf, off)
        off += 4
    answers = []
    for _ in range(an):
        name, off = decode_name(buf, off)
        qtype, qclass, ttl, rdlen = struct.unpack("!HHIH", buf[off:off + 10])
        off += 10
        rdata = buf[off:off + rdlen]
        off += rdlen
        if qtype == QTYPE_A and rdlen == 4:
            answers.append((name, qtype, socket.inet_ntoa(rdata)))
        elif qtype == QTYPE_SRV:
            prio, weight, port = struct.unpack("!HHH", rdata[:6])
            target, _ = decode_name(buf, off - rdlen + 6)
            answers.append((name, qtype, (prio, weight, port, target)))
        else:
            answers.append((name, qtype, rdata))
    return rcode, answers


class DNSServer:
    """UDP datagram server answering A/SRV from a DNSRecordStore."""

    def __init__(self, records: DNSRecordStore, host: str = "127.0.0.1",
                 port: int = 0, ttl: int = 30):
        self.records = records
        self.ttl = ttl
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self._sock.settimeout(0.2)
        self.address = self._sock.getsockname()  # (host, real port)
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"queries": 0, "nxdomain": 0}

    # -- wire building -------------------------------------------------------
    def _answer(self, buf: bytes) -> Optional[bytes]:
        if len(buf) < 12:
            return None
        (txid, flags, qd, _, _, _) = struct.unpack("!HHHHHH", buf[:12])
        opcode = (flags >> 11) & 0xF
        if opcode != 0 or qd < 1:
            header = struct.pack("!HHHHHH", txid, 0x8180 | RCODE_NOTIMPL, qd, 0, 0, 0)
            return header + buf[12:]
        qname, off = decode_name(buf, 12)
        qtype, qclass = struct.unpack("!HH", buf[off:off + 4])
        question = buf[12:off + 4]
        self.stats["queries"] += 1

        rrs = b""
        count = 0
        name_ptr = struct.pack("!H", 0xC000 | 12)  # compression → question
        if qclass == QCLASS_IN and qtype in (QTYPE_A, QTYPE_ANY):
            for ip in self.records.resolve(qname, "A"):
                rdata = socket.inet_aton(ip)
                rrs += name_ptr + struct.pack("!HHIH", QTYPE_A, QCLASS_IN,
                                              self.ttl, len(rdata)) + rdata
                count += 1
        if qclass == QCLASS_IN and qtype in (QTYPE_SRV, QTYPE_ANY):
            for port, target in self.records.resolve(qname, "SRV"):
                rdata = struct.pack("!HHH", 10, 10, port) + encode_name(target)
                rrs += name_ptr + struct.pack("!HHIH", QTYPE_SRV, QCLASS_IN,
                                              self.ttl, len(rdata)) + rdata
                count += 1
        rcode = RCODE_OK if count else RCODE_NXDOMAIN
        if not count:
            self.stats["nxdomain"] += 1
        # QR|AA|RD|RA + rcode
        header = struct.pack("!HHHHHH", txid, 0x8580 | rcode, 1, count, 0, 0)
        return header + question + rrs

    # -- serving -------------------------------------------------------------
    def serve_once(self) -> bool:
        try:
            buf, peer = self._sock.recvfrom(4096)
        except socket.timeout:
            return False
        try:
            resp = self._answer(buf)
        except Exception:
            # a malformed datagram (truncated QNAME, pointer loop, short
            # header) must never kill the serving thread — drop it
            self.stats["malformed"] = self.stats.get("malformed", 0) + 1
            return True
        if resp is not None:
            self._sock.sendto(resp, peer)
        return True

    def _loop(self) -> None:
        while not self._stopped.is_set():
            self.serve_once()

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._sock.close()


def lookup(server_addr: tuple, qname: str, qtype: str = "A", timeout: float = 2.0):
    """Client-side resolver: one UDP query against ``server_addr``.
    Returns the list DNSRecordStore.resolve would (IPs, or SRV tuples
    without priority/weight)."""
    qt = QTYPE_A if qtype == "A" else QTYPE_SRV
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(build_query(qname, qt), server_addr)
        buf, _ = s.recvfrom(4096)
    rcode, answers = parse_response(buf)
    if rcode != RCODE_OK:
        return []
    if qtype == "A":
        return [rd for _, t, rd in answers if t == QTYPE_A]
    return [(rd[2], rd[3]) for _, t, rd in answers if t == QTYPE_SRV]
