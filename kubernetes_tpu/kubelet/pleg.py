"""PLEG — the Pod Lifecycle Event Generator.

Capability of ``pkg/kubelet/pleg/generic.go:181 relist``: instead of the
sync loop polling every pod's runtime state, the PLEG periodically relists
the runtime (sandboxes + container states), diffs against the previous
relist, and emits typed lifecycle events; the kubelet syncs exactly the
pods that changed.  Out-of-band changes — a sandbox killed behind the
kubelet's back — surface as events within one relist period.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

CONTAINER_STARTED = "ContainerStarted"
CONTAINER_DIED = "ContainerDied"
SANDBOX_DIED = "SandboxDied"
POD_SYNC = "PodSync"


@dataclass(frozen=True)
class PodLifecycleEvent:
    pod_key: str
    type: str
    detail: str = ""


class PLEG:
    """Relist-based event source over the hollow runtime + sandboxes.

    ``relist()`` snapshots (sandbox liveness, per-container state/restart
    counts) for every known pod and emits the difference from the
    previous snapshot."""

    def __init__(self, pod_manager, sandboxes=None,
                 relist_period: float = 1.0,
                 clock: Callable[[], float] = None):
        import time

        self.pod_manager = pod_manager
        self.sandboxes = sandboxes
        self.relist_period = relist_period
        self.clock = clock or time.monotonic
        self._last_relist = -1e18
        # pod key -> {"sandbox": bool|None, "containers": {name: (state, restarts)}}
        self._snapshot: dict[str, dict] = {}
        self.stats = {"relists": 0, "events": 0}

    def due(self) -> bool:
        return self.clock() - self._last_relist >= self.relist_period

    def _observe(self) -> dict[str, dict]:
        snap: dict[str, dict] = {}
        for key in self.pod_manager.known():
            containers = {
                name: (st.status.state, st.status.restart_count)
                for name, st in self.pod_manager._pods.get(key, {}).items()
            }
            sandbox: Optional[bool] = None
            if self.sandboxes is not None and key in self.sandboxes.known():
                sandbox = self.sandboxes.exists(key)
            snap[key] = {"sandbox": sandbox, "containers": containers}
        return snap

    def relist(self, force: bool = False) -> list[PodLifecycleEvent]:
        if not force and not self.due():
            return []
        self._last_relist = self.clock()
        self.stats["relists"] += 1
        new = self._observe()
        events: list[PodLifecycleEvent] = []
        for key, cur in new.items():
            old = self._snapshot.get(key)
            if old is None:
                events.append(PodLifecycleEvent(key, POD_SYNC, "first relist"))
                continue
            # the out-of-band case: the sandbox process disappeared while
            # the runtime still believes the pod runs
            if old["sandbox"] is True and cur["sandbox"] is False:
                events.append(PodLifecycleEvent(
                    key, SANDBOX_DIED, "sandbox process gone"))
            for name, (state, restarts) in cur["containers"].items():
                prev = old["containers"].get(name)
                if prev is None:
                    events.append(PodLifecycleEvent(
                        key, CONTAINER_STARTED, name))
                    continue
                prev_state, prev_restarts = prev
                if restarts > prev_restarts:
                    # a restart implies died-then-started
                    events.append(PodLifecycleEvent(key, CONTAINER_DIED, name))
                    events.append(PodLifecycleEvent(
                        key, CONTAINER_STARTED, name))
                elif prev_state == "running" and state != "running":
                    events.append(PodLifecycleEvent(key, CONTAINER_DIED, name))
        for key in self._snapshot.keys() - new.keys():
            events.append(PodLifecycleEvent(key, POD_SYNC, "pod gone"))
        self._snapshot = new
        self.stats["events"] += len(events)
        return events
