"""Kubelet read API: the node's HTTP server (logs, healthz, pods).

Capability of ``pkg/kubelet/server`` (3,911 LoC) at this framework's
depth: the :10250 read surface the apiserver proxies pod subresources
to —

  GET /healthz
  GET /pods                                   (the node's pod list)
  GET /stats/summary                          (cadvisor-style usage)
  GET /containerLogs/{ns}/{pod}/{container}[?tailLines=N]
  POST /exec/{ns}/{pod}/{container}       {"command": [...]}

Exec is the CRI ExecSync capability: the reference streams over SPDY;
the command-in/stdout+exit-out contract rides JSON here.

Log content comes from the fake runtime's per-container buffers, which
the hollow kubelet writes lifecycle lines into (started/restarted/
probe failures) and tests/workloads can append to."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


class KubeletServer:
    def __init__(self, kubelet, host: str = "127.0.0.1", port: int = 0,
                 exec_token: str = ""):
        self.kubelet = kubelet
        # exec is a WRITE capability: when a token is set, exec requests
        # must present it (the reference kubelet delegates authn/authz to
        # the apiserver; the shared-secret bearer is that contract's
        # minimal form — the read-only endpoints stay open like :10255)
        self.exec_token = exec_token
        handler = _make_handler(kubelet, self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.httpd.server_address[0]}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


def _make_handler(kubelet, server_ref=None):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def _send(self, code: int, data: bytes, ctype: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            if url.path == "/healthz":
                return self._send(200, b"ok", "text/plain")
            if url.path == "/stats/summary":
                return self._send(200, json.dumps(kubelet.stats_summary()).encode())
            if url.path == "/pods":
                pods = [p.to_dict() for p in kubelet._my_pods()]
                return self._send(200, json.dumps({"items": pods}).encode())
            if len(parts) == 4 and parts[0] == "containerLogs":
                _, ns, pod, container = parts
                q = parse_qs(url.query)
                lines = kubelet.runtime.read_logs(f"{ns}/{pod}", container)
                if lines is None:
                    return self._send(404, b"container not found", "text/plain")
                tail = q.get("tailLines", [None])[0]
                if tail is not None:
                    if not tail.isdigit():
                        return self._send(400, b"tailLines must be an integer",
                                          "text/plain")
                    lines = lines[-int(tail):]
                return self._send(200, ("\n".join(lines) + "\n" if lines else "").encode(),
                                  "text/plain")
            if len(parts) == 4 and parts[0] == "attach":
                # attach = the container's live output stream; at this
                # depth (no TTY) it serves the stream so far, like the
                # reference's attach without stdin.  A silent container is
                # an EMPTY stream, not a 404 — existence is judged by the
                # pod spec, not by whether it has logged yet.
                _, ns, pod, container = parts
                key = f"{ns}/{pod}"
                target = next((p2 for p2 in kubelet._my_pods() if p2.meta.key == key), None)
                if target is None:
                    return self._send(404, b"pod not on this node", "text/plain")
                if container not in [c.name for c in target.spec.containers]:
                    return self._send(404, b"container not found", "text/plain")
                lines = kubelet.runtime.read_logs(key, container) or []
                return self._send(200, ("\n".join(lines) + "\n" if lines else "").encode(),
                                  "text/plain")
            if len(parts) == 4 and parts[0] == "cp":
                # cp READ is an exec-class capability too (it exfiltrates
                # container files): same token gate as exec/cp-write
                resolved = self._resolve_cp(parts)
                if resolved is None:
                    return
                key, container = resolved
                q = parse_qs(url.query)
                path = q.get("path", [""])[0]
                data = kubelet.runtime.read_file(key, container, path)
                if data is None:
                    return self._send(404, b"file not found", "text/plain")
                return self._send(200, data, "application/octet-stream")
            return self._send(404, b"not found", "text/plain")

        def _resolve_cp(self, parts):
            """Shared cp validation: exec token + pod-on-node +
            container-in-spec (the same gates exec/attach apply).  Returns
            (pod_key, container) or None after writing the error."""
            token = server_ref.exec_token
            if token:
                auth = self.headers.get("Authorization", "")
                if auth != f"Bearer {token}":
                    self._send(401, b"unauthorized", "text/plain")
                    return None
            _, ns, pod, container = parts
            key = f"{ns}/{pod}"
            target = next((p2 for p2 in kubelet._my_pods() if p2.meta.key == key), None)
            if target is None:
                self._send(404, b"pod not on this node", "text/plain")
                return None
            if container not in [c.name for c in target.spec.containers]:
                self._send(404, b"container not found", "text/plain")
                return None
            return key, container

        def do_PUT(self):
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            if len(parts) == 4 and parts[0] == "cp":
                resolved = self._resolve_cp(parts)
                if resolved is None:
                    return
                key, container = resolved
                q = parse_qs(url.query)
                path = q.get("path", [""])[0]
                if not path:
                    return self._send(400, b"path required", "text/plain")
                length = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(length) if length else b""
                kubelet.runtime.write_file(key, container, path, data)
                return self._send(200, b"{}")
            return self._send(404, b"not found", "text/plain")

        def do_POST(self):
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            if len(parts) == 4 and parts[0] == "exec":
                token = server_ref.exec_token
                if token:
                    auth = self.headers.get("Authorization", "")
                    if auth != f"Bearer {token}":
                        return self._send(401, b"unauthorized", "text/plain")
                _, ns, pod, container = parts
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length)) if length else {}
                except ValueError:
                    return self._send(400, b"bad json", "text/plain")
                command = body.get("command") or []
                if not isinstance(command, list) or not command:
                    return self._send(400, b"command required", "text/plain")
                key = f"{ns}/{pod}"
                target = next((p2 for p2 in kubelet._my_pods() if p2.meta.key == key), None)
                if target is None:
                    return self._send(404, b"pod not on this node", "text/plain")
                if container not in [c.name for c in target.spec.containers]:
                    return self._send(404, b"container not found", "text/plain")
                stdout, code = kubelet.runtime.exec(key, container, [str(c) for c in command])
                out = json.dumps({"stdout": stdout, "exitCode": int(code)}).encode()
                return self._send(200, out)
            return self._send(404, b"not found", "text/plain")

    return Handler
