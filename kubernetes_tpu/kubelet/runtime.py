"""Fake container runtime + prober + pressure eviction for the hollow node.

Capability of three reference kubelet subsystems, driven by the hollow
kubelet's tick (no containers underneath — a scriptable fake runtime
plays the part of dockershim, like kubemark's fake Docker client):

- **Prober** (``pkg/kubelet/prober/``, 905 LoC): per-container liveness
  and readiness workers honoring ``initialDelaySeconds`` /
  ``periodSeconds`` / ``failureThreshold`` / ``successThreshold``.
  Liveness failure past the threshold restarts the container
  (restart_count += 1); readiness results drive the container's
  ``ready`` bit and the pod's Ready condition — which the endpoint
  controller consumes, so an unready pod leaves its Service.
- **Restart policy** (``kuberuntime_manager.go SyncPod``): a container
  exit restarts under Always (and OnFailure when exit_code != 0);
  otherwise the pod goes Succeeded/Failed.
- **Eviction manager** (``pkg/kubelet/eviction/eviction_manager.go:213
  synchronize``): observed memory/disk signals against thresholds; when
  over, pods are ranked — BestEffort first, then Burstable, Guaranteed
  last (the QoS order of ``eviction/helpers.go``), higher usage first
  within a class — and evicted (phase Failed, reason Evicted) until the
  signal clears; the node reports Memory/DiskPressure conditions, which
  the scheduler's CheckNodeMemoryPressure / CheckNodeDiskPressure
  predicates then act on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api import types as api

QOS_GUARANTEED = "Guaranteed"
QOS_BURSTABLE = "Burstable"
QOS_BEST_EFFORT = "BestEffort"

_QOS_EVICTION_ORDER = {QOS_BEST_EFFORT: 0, QOS_BURSTABLE: 1, QOS_GUARANTEED: 2}


def pod_qos_class(pod: api.Pod) -> str:
    """Reference ``pkg/api/v1/helper/qos.GetPodQOS``."""
    requests: dict[str, str] = {}
    limits_all = True
    any_request = False
    for c in pod.spec.containers:
        r, l = c.resources.requests, c.resources.limits
        if r:
            any_request = True
        for k in ("cpu", "memory"):
            rq, lq = r.get(k), l.get(k)
            if lq is None or (rq is not None and str(rq) != str(lq)):
                limits_all = False
    if not any_request and not any(c.resources.limits for c in pod.spec.containers):
        return QOS_BEST_EFFORT
    if limits_all and all(
        c.resources.requests.keys() == c.resources.limits.keys() or not c.resources.requests
        for c in pod.spec.containers
    ) and all(c.resources.limits for c in pod.spec.containers):
        return QOS_GUARANTEED
    return QOS_BURSTABLE


@dataclass
class _ProbeState:
    consecutive_failures: int = 0
    consecutive_successes: int = 0
    last_run: float = -1e18
    result: bool = True  # last settled verdict


@dataclass
class _ContainerState:
    status: api.ContainerStatus = None
    liveness: _ProbeState = field(default_factory=_ProbeState)
    readiness: _ProbeState = field(default_factory=_ProbeState)
    started_at: float = 0.0


class FakeRuntime:
    """Scriptable container world: tests flip probe outcomes and inject
    exits; the prober/eviction logic reacts exactly as the real kubelet
    would against CRI."""

    def __init__(self):
        # (pod_key, container) -> scripted outcome
        self.probe_results: dict[tuple[str, str, str], bool] = {}
        self.exits: dict[tuple[str, str], int] = {}  # -> exit code
        # per-pod observed usage signals (the cadvisor stand-in)
        self.pod_memory_usage: dict[str, int] = {}  # bytes
        # (pod_key, container) -> log lines (the container stdout stand-in)
        self._logs: dict[tuple[str, str], list[str]] = {}
        # (pod_key, container) -> exec handler (the CRI ExecSync stand-in)
        self._exec_handlers: dict = {}
        # (pod_key, container) -> {path: bytes} — the container filesystem
        # stand-in backing ``kubectl cp`` (the reference streams tar over
        # exec; the capability is per-container file read/write)
        self._files: dict[tuple[str, str], dict[str, bytes]] = {}
        # real-container delegates (set by the kubelet when containers are
        # real processes, kubelet/containers.py): scripted handlers/files
        # still take precedence so tests keep their override seam
        self.exec_delegate = None  # fn(pod_key, container, command) -> (out, rc)
        self.log_delegate = None  # fn(pod_key, container) -> list[str] | None
        self.file_read_delegate = None  # fn(pod_key, container, path) -> bytes|None
        self.file_write_delegate = None  # fn(pod_key, container, path, data) -> bool

    def write_file(self, pod_key: str, container: str, path: str, data: bytes) -> None:
        if self.file_write_delegate is not None:
            if self.file_write_delegate(pod_key, container, path, bytes(data)):
                return
        self._files.setdefault((pod_key, container), {})[path] = bytes(data)

    def read_file(self, pod_key: str, container: str, path: str):
        """Bytes, or None if absent."""
        data = self._files.get((pod_key, container), {}).get(path)
        if data is None and self.file_read_delegate is not None:
            data = self.file_read_delegate(pod_key, container, path)
        return data

    def append_log(self, pod_key: str, container: str, line: str) -> None:
        self._logs.setdefault((pod_key, container), []).append(line)

    def read_logs(self, pod_key: str, container: str):
        """Lines, or None if the container never existed here.  Real
        containers contribute their process stdout/stderr after the
        kubelet's lifecycle lines."""
        lines = self._logs.get((pod_key, container))
        if self.log_delegate is not None:
            real = self.log_delegate(pod_key, container)
            if real is not None:
                lines = (lines or []) + real
        return lines

    def drop_logs(self, pod_key: str) -> None:
        for k in [k for k in self._logs if k[0] == pod_key]:
            del self._logs[k]

    def set_exec_handler(self, pod_key: str, container: str, fn) -> None:
        """fn(command: list[str]) -> (stdout: str, exit_code: int)."""
        self._exec_handlers[(pod_key, container)] = fn

    def exec(self, pod_key: str, container: str, command: list[str]):
        """Run a command "in" the container (CRI ExecSync).  Scripted
        handlers override; real containers (delegate) run the command as
        an actual child process; the fake echoes."""
        fn = self._exec_handlers.get((pod_key, container))
        if fn is not None:
            return fn(command)
        if self.exec_delegate is not None:
            return self.exec_delegate(pod_key, container, command)
        return (" ".join(command), 0)

    def probe(self, pod_key: str, container: str, kind: str) -> bool:
        return self.probe_results.get((pod_key, container, kind), True)

    def set_probe(self, pod_key: str, container: str, kind: str, ok: bool) -> None:
        self.probe_results[(pod_key, container, kind)] = ok

    def inject_exit(self, pod_key: str, container: str, exit_code: int) -> None:
        self.exits[(pod_key, container)] = exit_code

    def take_exit(self, pod_key: str, container: str) -> Optional[int]:
        return self.exits.pop((pod_key, container), None)


class PodRuntimeManager:
    """Per-kubelet container/probe state machine (one per HollowKubelet).

    With ``containers`` (a :class:`~kubernetes_tpu.kubelet.containers.
    ProcessContainerManager`) and optionally ``volume_host``, containers
    are REAL child processes: start forks them, sync polls their pids
    (an out-of-band ``kill -9`` is a container death), restart spawns a
    fresh process, and exec probes run through CRI ExecSync
    (``prober/prober.go:80``)."""

    def __init__(self, runtime: FakeRuntime, clock: Callable[[], float],
                 containers=None, volume_host=None):
        self.runtime = runtime
        self.clock = clock
        self.containers = containers
        self.volume_host = volume_host
        self._pods: dict[str, dict[str, _ContainerState]] = {}

    def _spawn(self, pod: api.Pod, c: api.Container) -> str:
        """Start the real child for container ``c``; returns its
        "pid://<n>" id.  Volumes are materialized and projected into the
        rootfs FIRST — the entrypoint may read them immediately."""
        key = pod.meta.key
        import os as _os

        rootfs = self.containers.rootfs(key, c.name)
        _os.makedirs(rootfs, exist_ok=True)
        if self.volume_host is not None:
            self.volume_host.sync_pod(pod)
            self.volume_host.project_into_rootfs(pod, c, rootfs)
        pid = self.containers.start(key, c.name,
                                    command=c.command or None, env=c.env)
        return f"pid://{pid}"

    def ensure_running(self, pod: api.Pod) -> None:
        key = pod.meta.key
        if key in self._pods:
            return
        now = self.clock()
        self._pods[key] = {}
        for c in pod.spec.containers:
            cid = ""
            if self.containers is not None:
                cid = self._spawn(pod, c)
            self._pods[key][c.name] = _ContainerState(
                status=api.ContainerStatus(name=c.name, state="running",
                                           ready=True, container_id=cid),
                started_at=now,
            )
            self.runtime.append_log(key, c.name, f"container {c.name} started")

    def forget(self, pod_key: str) -> None:
        self._pods.pop(pod_key, None)
        # a recreated pod under the same key must not inherit old logs,
        # and a churning fleet must not grow buffers without bound
        self.runtime.drop_logs(pod_key)
        if self.containers is not None:
            self.containers.remove_pod(pod_key)
        if self.volume_host is not None:
            self.volume_host.teardown_pod(pod_key)

    def known(self) -> set[str]:
        return set(self._pods)

    # -- one prober + runtime pass for one pod; returns the pod-level
    # outcome: ("running", statuses, all_ready) | ("succeeded"|"failed", ...)
    def sync_pod(self, pod: api.Pod):
        key = pod.meta.key
        states = self._pods.get(key)
        if states is None:
            self.ensure_running(pod)
            states = self._pods[key]
        now = self.clock()
        terminal: Optional[str] = None

        if self.volume_host is not None:
            # mount reconciler pass (reconciler.go:165): configMap/secret
            # updates re-materialize while the pod runs — the atomic
            # symlink flip makes the new content visible in-place
            self.volume_host.sync_pod(pod)
        for c in pod.spec.containers:
            st = states.get(c.name)
            if st is None:
                cid = self._spawn(pod, c) if self.containers is not None else ""
                st = states[c.name] = _ContainerState(
                    status=api.ContainerStatus(name=c.name, state="running",
                                               ready=True, container_id=cid),
                    started_at=now,
                )
            # scripted exit (the PLEG event); under the real runtime the
            # kernel is the truth — a process that exited or was killed
            # out-of-band (kill -9) surfaces here via waitpid
            exit_code = self.runtime.take_exit(key, c.name)
            if (exit_code is None and self.containers is not None
                    and st.status.state == "running"
                    and not self.containers.alive(key, c.name)):
                exit_code = self.containers.exit_code(key, c.name)
                if exit_code is None:
                    exit_code = 137  # unknown death: report like SIGKILL
            if exit_code is not None:
                restart = pod.spec.restart_policy == "Always" or (
                    pod.spec.restart_policy == "OnFailure" and exit_code != 0
                )
                if restart:
                    self._restart(st, now, reason="Error" if exit_code else "Completed", pod_key=key, cname=c.name, spec=c)
                else:
                    st.status.state = "terminated"
                    st.status.ready = False
                    st.status.exit_code = exit_code
                    st.status.reason = "Error" if exit_code else "Completed"
                    terminal = "failed" if exit_code else "succeeded"
                continue
            if st.status.state != "running":
                continue
            # liveness: failureThreshold consecutive failures -> restart
            if c.liveness_probe is not None:
                res = self._run_probe(st, st.liveness, c.liveness_probe, key, c.name, "liveness", now)
                if res is False and st.liveness.consecutive_failures >= c.liveness_probe.failure_threshold:
                    self._restart(st, now, reason="Unhealthy", pod_key=key, cname=c.name, spec=c)
            # readiness: drives the ready bit through both thresholds
            if c.readiness_probe is not None:
                self._run_probe(st, st.readiness, c.readiness_probe, key, c.name, "readiness", now)
                st.status.ready = st.readiness.result and st.status.state == "running"
            else:
                st.status.ready = st.status.state == "running"

        statuses = [states[c.name].status for c in pod.spec.containers if c.name in states]
        all_ready = bool(statuses) and all(s.ready for s in statuses)
        if terminal is not None:
            return terminal, statuses, False
        return "running", statuses, all_ready

    def _run_probe(self, cst: _ContainerState, pst: _ProbeState, probe: api.Probe,
                   pod_key: str, cname: str, kind: str, now: float) -> Optional[bool]:
        if now - cst.started_at < probe.initial_delay_seconds:
            return None
        if now - pst.last_run < probe.period_seconds:
            return None
        pst.last_run = now
        scripted = self.runtime.probe_results.get((pod_key, cname, kind))
        if scripted is not None:
            ok = scripted  # tests' override seam always wins
        elif self.containers is not None and probe.exec_command:
            # real exec probe: run the command via ExecSync and judge by
            # exit code (prober/prober.go:80 runProbe).  The wait is
            # bounded by the probe's own timeoutSeconds (reference
            # default 1s) — probes run inline in the serial sync tick, so
            # a wedged command costs at most that bound per period
            try:
                _, rc = self.containers.exec_sync(
                    pod_key, cname, probe.exec_command,
                    timeout=max(0.1, float(probe.timeout_seconds)))
                ok = rc == 0
            except ValueError:  # container not running
                ok = False
        else:
            ok = self.runtime.probe(pod_key, cname, kind)
        if ok:
            pst.consecutive_successes += 1
            pst.consecutive_failures = 0
            if pst.consecutive_successes >= probe.success_threshold:
                pst.result = True
        else:
            pst.consecutive_failures += 1
            pst.consecutive_successes = 0
            if pst.consecutive_failures >= probe.failure_threshold:
                pst.result = False
        return ok

    def _restart(self, st: _ContainerState, now: float, reason: str,
                 pod_key: str, cname: str,
                 spec: Optional[api.Container] = None) -> None:
        if self.containers is not None:
            # reap the dead (or unhealthy) process and fork a FRESH one —
            # the restarted container has a genuinely new pid
            self.containers.remove(pod_key, cname)
            pid = self.containers.start(
                pod_key, cname,
                command=(spec.command or None) if spec is not None else None,
                env=spec.env if spec is not None else None)
            st.status.container_id = f"pid://{pid}"
        st.status.restart_count += 1
        st.status.state = "running"
        st.status.ready = True
        st.status.reason = reason
        st.started_at = now
        st.liveness = _ProbeState()
        st.readiness = _ProbeState()
        self.runtime.append_log(
            pod_key, cname,
            f"container {cname} restarted ({reason}), restart #{st.status.restart_count}",
        )


def rank_for_eviction(pods: list[api.Pod], usage: dict[str, int]) -> list[api.Pod]:
    """QoS class first (BestEffort evicted first), then usage descending
    (``eviction/helpers.go`` rankMemoryPressure)."""
    return sorted(
        pods,
        key=lambda p: (
            _QOS_EVICTION_ORDER.get(pod_qos_class(p), 1),
            -usage.get(p.meta.key, 0),
        ),
    )


class ProcessSandboxManager:
    """Real pod sandboxes: one ``ktpu-pause`` process per pod.

    The reference's RunPodSandbox starts the pause container before any
    workload container (``kuberuntime_sandbox.go``); pause holds the
    sandbox's namespaces and reaps re-parented zombies
    (``build/pause/pause.c``).  This manager does the same with the
    compiled ``csrc/pause.c`` — giving the hollow node a REAL process
    backbone when enabled, so sandbox lifecycle (create/exists/remove,
    TERM teardown) is exercised against the actual kernel instead of a
    dict.  Falls back to inert (no processes) when no C toolchain built
    the binary."""

    def __init__(self):
        import atexit
        import subprocess

        from ..native import pause_binary

        self._subprocess = subprocess
        self._bin = pause_binary()
        self._procs: dict[str, object] = {}
        if self._bin is not None:
            # pause sleeps forever: without this, an interpreter exit with
            # running sandboxes leaves one orphan OS process per pod
            atexit.register(self.remove_all)

    @property
    def enabled(self) -> bool:
        return self._bin is not None

    def create(self, pod_key: str) -> Optional[int]:
        """Idempotent RunPodSandbox: returns the sandbox pid (None when
        disabled)."""
        if self._bin is None:
            return None
        proc = self._procs.get(pod_key)
        if proc is not None and proc.poll() is None:
            return proc.pid
        proc = self._subprocess.Popen(
            [self._bin],
            stdout=self._subprocess.DEVNULL,
            stderr=self._subprocess.DEVNULL,
        )
        self._procs[pod_key] = proc
        return proc.pid

    def exists(self, pod_key: str) -> bool:
        proc = self._procs.get(pod_key)
        return proc is not None and proc.poll() is None

    def known(self) -> set:
        """Keys with a sandbox (live or pending reap) — the public view
        the kubelet's GC pass diffs against."""
        return set(self._procs)

    def remove(self, pod_key: str, timeout: float = 5.0) -> None:
        """StopPodSandbox + RemovePodSandbox: TERM, wait, KILL on
        overrun."""
        proc = self._procs.pop(pod_key, None)
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except self._subprocess.TimeoutExpired:
            proc.kill()
            try:
                # KILL is eventually fatal; a process stuck in D-state
                # past this wait must not abort the caller's sweep and
                # orphan every sandbox after it
                proc.wait(timeout=timeout)
            except self._subprocess.TimeoutExpired:
                pass

    def remove_all(self) -> None:
        for key in list(self._procs):
            self.remove(key)
