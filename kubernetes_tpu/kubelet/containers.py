"""Real containers: one child process per container, under the pod's
pause sandbox.

Capability of the reference's runtime manager + dockershim slice that is
feasible on one unprivileged machine (``pkg/kubelet/kuberuntime/
kuberuntime_manager.go:530 SyncPod`` computing container actions;
``pkg/kubelet/dockershim`` running them):

- **create/start** — each container is a REAL forked child
  (``/bin/sh -c <command>``) with the container's env, its own rootfs
  directory (where volume mounts materialize, see ``volumehost.py``),
  and stdout/stderr appended to a per-container log file;
- **stop** — TERM, bounded wait, KILL (the runtime's graceful-stop
  contract);
- **exec_sync** — runs a command in the container's context (rootfs cwd
  + env), the CRI ``ExecSync`` the prober and ``kubectl exec`` ride
  (``prober/prober.go:80`` judges by exit code);
- **poll** — observed state from the kernel (``waitpid``), so an
  out-of-band ``kill -9`` surfaces as a container death the next sync,
  exactly like the PLEG discovering a dead container in a relist.

There is no namespace/cgroup isolation here (unprivileged box); what IS
real: pids, the process tree, exit codes, signals, the filesystem, and
exec.  The pod's pause process (``csrc/pause.c``) still anchors the
sandbox; containers are tracked per sandbox and die with it.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import tempfile
import threading
from typing import Optional

# default entrypoint: a quiet long sleep (the "image default" — pause-like)
_DEFAULT_COMMAND = ["/bin/sh", "-c", "exec sleep 1000000"]


class ProcessContainerManager:
    """Real child processes playing the container role (one per
    (pod, container)); rootfs dirs under a private temp root."""

    def __init__(self, root: Optional[str] = None):
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="ktpu-containers-")
        self._mu = threading.Lock()
        # (pod_key, name) -> {"proc": Popen, "rootfs": str, "env": dict,
        #                     "log": str, "command": list}
        self._ctrs: dict[tuple[str, str], dict] = {}
        import atexit

        atexit.register(self.remove_all)

    # -- paths ---------------------------------------------------------------
    def pod_dir(self, pod_key: str) -> str:
        return os.path.join(self.root, pod_key.replace("/", "_"))

    def rootfs(self, pod_key: str, name: str) -> str:
        return os.path.join(self.pod_dir(pod_key), "containers", name, "rootfs")

    def log_path(self, pod_key: str, name: str) -> str:
        return os.path.join(self.pod_dir(pod_key), "containers", name, "log")

    # -- lifecycle -----------------------------------------------------------
    def start(self, pod_key: str, name: str, command: Optional[list[str]] = None,
              env: Optional[dict] = None) -> int:
        """CreateContainer + StartContainer: fork the real child; returns
        its pid.  A container already alive under this identity is left
        running (idempotent sync)."""
        with self._mu:
            cur = self._ctrs.get((pod_key, name))
            if cur is not None and cur["proc"].poll() is None:
                return cur["proc"].pid
            rootfs = self.rootfs(pod_key, name)
            os.makedirs(rootfs, exist_ok=True)
            log = self.log_path(pod_key, name)
            cmd = list(command) if command else list(_DEFAULT_COMMAND)
            full_env = dict(os.environ)
            full_env.update(env or {})
            full_env["KTPU_POD"] = pod_key
            full_env["KTPU_CONTAINER"] = name
            full_env["KTPU_ROOTFS"] = rootfs
            logf = open(log, "ab", buffering=0)
            try:
                try:
                    proc = subprocess.Popen(
                        cmd, cwd=rootfs, env=full_env,
                        stdout=logf, stderr=logf,
                        stdin=subprocess.DEVNULL,
                        start_new_session=True,  # own pgid: stop() signals the tree
                    )
                except OSError as e:
                    # an unrunnable entrypoint must not abort the caller's
                    # sync sweep (reference: CreateContainerError feeding
                    # CrashLoopBackOff).  A real child that exits 127
                    # keeps every downstream path uniform: the death is
                    # kernel-observed, restart policy cycles it, the
                    # error is in the log.
                    logf.write(f"spawn failed: {e}\n".encode())
                    proc = subprocess.Popen(
                        ["/bin/sh", "-c", "exit 127"], cwd=rootfs,
                        env=full_env, stdout=logf, stderr=logf,
                        stdin=subprocess.DEVNULL, start_new_session=True,
                    )
            finally:
                logf.close()  # the child holds its own fd now
            self._ctrs[(pod_key, name)] = {
                "proc": proc, "rootfs": rootfs, "env": dict(env or {}),
                "log": log, "command": cmd,
            }
            return proc.pid

    def pid(self, pod_key: str, name: str) -> Optional[int]:
        with self._mu:
            c = self._ctrs.get((pod_key, name))
            return None if c is None else c["proc"].pid

    def alive(self, pod_key: str, name: str) -> bool:
        with self._mu:
            c = self._ctrs.get((pod_key, name))
            return c is not None and c["proc"].poll() is None

    def exit_code(self, pod_key: str, name: str) -> Optional[int]:
        """None while running (or unknown); the real wait status once
        dead.  A kill by signal N reports 128+N like a shell would."""
        with self._mu:
            c = self._ctrs.get((pod_key, name))
            if c is None:
                return None
            rc = c["proc"].poll()
            if rc is None:
                return None
            return 128 - rc if rc < 0 else rc

    def stop(self, pod_key: str, name: str, timeout: float = 5.0) -> None:
        with self._mu:
            c = self._ctrs.get((pod_key, name))
        if c is None:
            return
        proc = c["proc"]
        if proc.poll() is None:
            try:  # signal the whole process group (shell + children)
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                proc.terminate()
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    proc.kill()
                try:
                    proc.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    pass  # D-state straggler; never block the sweep

    def remove(self, pod_key: str, name: str) -> None:
        self.stop(pod_key, name)
        with self._mu:
            self._ctrs.pop((pod_key, name), None)

    def remove_pod(self, pod_key: str) -> None:
        with self._mu:
            names = [n for (k, n) in self._ctrs if k == pod_key]
        for n in names:
            self.remove(pod_key, n)
        shutil.rmtree(self.pod_dir(pod_key), ignore_errors=True)

    def remove_all(self) -> None:
        with self._mu:
            keys = list(self._ctrs)
        for k, n in keys:
            self.remove(k, n)
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)

    def known_pods(self) -> set[str]:
        with self._mu:
            return {k for (k, _) in self._ctrs}

    # -- exec ---------------------------------------------------------------
    def exec_sync(self, pod_key: str, name: str, command: list[str],
                  timeout: float = 10.0) -> tuple[str, int]:
        """CRI ExecSync: run ``command`` in the container's context
        (rootfs cwd, container env).  Like the reference, exec into a
        dead container is an error (ValueError -> the server's 4xx)."""
        with self._mu:
            c = self._ctrs.get((pod_key, name))
            if c is None or c["proc"].poll() is not None:
                raise ValueError(f"container {pod_key}/{name} is not running")
            rootfs, env = c["rootfs"], dict(c["env"])
        full_env = dict(os.environ)
        full_env.update(env)
        full_env["KTPU_POD"] = pod_key
        full_env["KTPU_CONTAINER"] = name
        full_env["KTPU_ROOTFS"] = rootfs
        try:
            res = subprocess.run(
                command, cwd=rootfs, env=full_env, stdin=subprocess.DEVNULL,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return ("exec timed out", 124)
        except (FileNotFoundError, PermissionError) as e:
            return (str(e), 126)
        return (res.stdout.decode(errors="replace"), res.returncode)

    # -- observed usage (the cadvisor slice: /proc is the source) ------------
    def usage(self, pod_key: str) -> dict:
        """Kernel-observed usage summed over the pod's live container
        processes: RSS bytes (``/proc/<pid>/status`` VmRSS) and
        cumulative CPU milliseconds (``/proc/<pid>/stat`` utime+stime).
        The stats-summary endpoint serves this; a metrics client turns
        the cumulative CPU into a rate by sampling twice."""
        with self._mu:
            pids = [c["proc"].pid for (k, _), c in self._ctrs.items()
                    if k == pod_key and c["proc"].poll() is None]
        rss = 0
        cpu_ms = 0.0
        tick = os.sysconf("SC_CLK_TCK") or 100
        for pid in pids:
            try:
                with open(f"/proc/{pid}/status") as f:
                    for line in f:
                        if line.startswith("VmRSS:"):
                            rss += int(line.split()[1]) * 1024
                            break
                with open(f"/proc/{pid}/stat") as f:
                    fields = f.read().rsplit(")", 1)[1].split()
                    # utime=field 14, stime=15 (1-indexed); after ')' the
                    # split starts at field 3
                    cpu_ms += (int(fields[11]) + int(fields[12])) / tick * 1000.0
            except (OSError, IndexError, ValueError):
                continue  # raced a death; skip
        return {"memoryBytes": rss, "cpuMillis": cpu_ms}

    def read_log(self, pod_key: str, name: str) -> Optional[list[str]]:
        path = self.log_path(pod_key, name)
        try:
            with open(path, "rb") as f:
                text = f.read().decode(errors="replace")
        except FileNotFoundError:
            return None
        return text.splitlines()
