"""Real containers: one child process per container, under the pod's
pause sandbox — with on-disk checkpoints for kubelet restart recovery.

Capability of the reference's runtime manager + dockershim slice that is
feasible on one unprivileged machine (``pkg/kubelet/kuberuntime/
kuberuntime_manager.go:530 SyncPod`` computing container actions;
``pkg/kubelet/dockershim`` running them):

- **create/start** — each container is a REAL forked child
  (``/bin/sh -c <command>``) with the container's env, its own rootfs
  directory (where volume mounts materialize, see ``volumehost.py``),
  and stdout/stderr appended to a per-container log file;
- **stop** — TERM, bounded wait, KILL (the runtime's graceful-stop
  contract);
- **exec_sync** — runs a command in the container's context (rootfs cwd
  + env), the CRI ``ExecSync`` the prober and ``kubectl exec`` ride
  (``prober/prober.go:80`` judges by exit code);
- **poll** — observed state from the kernel (``waitpid``), so an
  out-of-band ``kill -9`` surfaces as a container death the next sync,
  exactly like the PLEG discovering a dead container in a relist.

There is no namespace/cgroup isolation here (unprivileged box); what IS
real: pids, the process tree, exit codes, signals, the filesystem, and
exec.  The pod's pause process (``csrc/pause.c``) still anchors the
sandbox; containers are tracked per sandbox and die with it.

**Checkpoints** (reference ``pkg/kubelet/dockershim/checkpoint_store.go``
/ ``docker_checkpoint.go``, exercised by
``e2e_node/dockershim_checkpoint_test.go``): every started container
writes ``checkpoint.json`` (pid + /proc start time + command/env) next
to its rootfs.  A manager constructed over the SAME root adopts the
still-live processes — a restarted kubelet resumes managing running
containers instead of orphaning them.  Adopted entries carry no Popen
handle (the new process cannot waitpid another's child), so liveness is
judged by /proc with the start-time pinned against pid reuse, and an
adopted death reports 137 (unknown), like a runtime that lost the wait
status.
"""

from __future__ import annotations

import atexit
import json
import os
import shutil
import signal
import subprocess
import tempfile
import threading
from typing import Optional


def _proc_stat(pid: int) -> tuple[Optional[str], Optional[str]]:
    """(state, starttime) from /proc/<pid>/stat — the birth stamp guards
    against pid reuse; the state char distinguishes a live process from a
    zombie (an unreaped dead child still has a /proc entry)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(")", 1)[1].split()
            return fields[0], fields[19]
    except (OSError, IndexError):
        return None, None


def _proc_starttime(pid: int) -> Optional[str]:
    return _proc_stat(pid)[1]

# default entrypoint: a quiet long sleep (the "image default" — pause-like)
_DEFAULT_COMMAND = ["/bin/sh", "-c", "exec sleep 1000000"]

# ONE module-level atexit hook over a strong set of managers:
# per-instance atexit.register pinned every manager (fleets, test
# suites) alive until interpreter exit even after remove_all.  The set
# must hold strong refs — a weak set would let a manager dropped
# WITHOUT remove_all be collected mid-run, orphaning its children
# forever; here it stays pinned until exit cleanup kills them, and
# remove_all() unpins the well-behaved ones.
_LIVE_MANAGERS: "set[ProcessContainerManager]" = set()


def _atexit_cleanup_all() -> None:
    for mgr in list(_LIVE_MANAGERS):
        mgr._atexit_cleanup()


atexit.register(_atexit_cleanup_all)


class ProcessContainerManager:
    """Real child processes playing the container role (one per
    (pod, container)); rootfs dirs under a private temp root."""

    def __init__(self, root: Optional[str] = None):
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="ktpu-containers-")
        self._mu = threading.Lock()
        # (pod_key, name) -> {"proc": Popen|None, "pid": int,
        #   "starttime": str|None, "rootfs": str, "env": dict,
        #   "log": str, "command": list}
        # proc is None for ADOPTED containers (checkpoint recovery): the
        # restarted manager watches them through /proc instead of waitpid
        self._ctrs: dict[tuple[str, str], dict] = {}
        self.stats = {"adopted": 0}
        _LIVE_MANAGERS.add(self)

    def _atexit_cleanup(self) -> None:
        """Ephemeral roots tear everything down; a PERSISTENT root leaves
        live containers and their checkpoints in place — that is the
        whole point of checkpoint recovery (a graceful kubelet exit must
        not kill the workloads a restart would re-adopt)."""
        if self._own_root:
            self.remove_all()

    # -- paths ---------------------------------------------------------------
    def pod_dir(self, pod_key: str) -> str:
        return os.path.join(self.root, pod_key.replace("/", "_"))

    def rootfs(self, pod_key: str, name: str) -> str:
        return os.path.join(self.pod_dir(pod_key), "containers", name, "rootfs")

    def log_path(self, pod_key: str, name: str) -> str:
        return os.path.join(self.pod_dir(pod_key), "containers", name, "log")

    def checkpoint_path(self, pod_key: str, name: str) -> str:
        return os.path.join(self.pod_dir(pod_key), "containers", name,
                            "checkpoint.json")

    # -- lifecycle -----------------------------------------------------------
    def start(self, pod_key: str, name: str, command: Optional[list[str]] = None,
              env: Optional[dict] = None) -> int:
        """CreateContainer + StartContainer: fork the real child; returns
        its pid.  A container already alive under this identity is left
        running (idempotent sync)."""
        _LIVE_MANAGERS.add(self)  # a manager reused after remove_all()
        # must regain exit cleanup for its new children
        with self._mu:
            cur = self._ctrs.get((pod_key, name))
            if cur is not None and self._alive_locked(cur):
                return cur["pid"]
            rootfs = self.rootfs(pod_key, name)
            os.makedirs(rootfs, exist_ok=True)
            log = self.log_path(pod_key, name)
            cmd = list(command) if command else list(_DEFAULT_COMMAND)
            full_env = dict(os.environ)
            full_env.update(env or {})
            full_env["KTPU_POD"] = pod_key
            full_env["KTPU_CONTAINER"] = name
            full_env["KTPU_ROOTFS"] = rootfs
            logf = open(log, "ab", buffering=0)
            try:
                try:
                    # the spawn must stay inside the idempotency check's
                    # lock hold: releasing between _alive_locked and Popen
                    # would let two concurrent sync sweeps double-start it
                    # blocking-ok — atomic check-then-spawn under _mu IS the idempotency contract
                    proc = subprocess.Popen(
                        cmd, cwd=rootfs, env=full_env,
                        stdout=logf, stderr=logf,
                        stdin=subprocess.DEVNULL,
                        start_new_session=True,  # own pgid: stop() signals the tree
                    )
                except OSError as e:
                    # an unrunnable entrypoint must not abort the caller's
                    # sync sweep (reference: CreateContainerError feeding
                    # CrashLoopBackOff).  A real child that exits 127
                    # keeps every downstream path uniform: the death is
                    # kernel-observed, restart policy cycles it, the
                    # error is in the log.
                    logf.write(f"spawn failed: {e}\n".encode())
                    # blocking-ok — same lock-hold contract as the spawn above
                    proc = subprocess.Popen(
                        ["/bin/sh", "-c", "exit 127"], cwd=rootfs,
                        env=full_env, stdout=logf, stderr=logf,
                        stdin=subprocess.DEVNULL, start_new_session=True,
                    )
            finally:
                logf.close()  # the child holds its own fd now
            entry = {
                "proc": proc, "pid": proc.pid,
                "starttime": _proc_starttime(proc.pid),
                "rootfs": rootfs, "env": dict(env or {}),
                "log": log, "command": cmd,
            }
            self._ctrs[(pod_key, name)] = entry
            # checkpoint for restart recovery (dockershim checkpoint_store)
            try:
                with open(self.checkpoint_path(pod_key, name), "w") as f:
                    json.dump({"pod": pod_key, "name": name,
                               "pid": entry["pid"],
                               "starttime": entry["starttime"],
                               "command": cmd, "env": dict(env or {})}, f)
            except OSError:
                pass  # a missing checkpoint only degrades restart adoption
            return proc.pid

    @staticmethod
    def _alive_locked(c: dict) -> bool:
        if c["proc"] is not None:
            return c["proc"].poll() is None
        # adopted: /proc liveness with the start time pinned (pid reuse)
        # and zombies excluded (dead-but-unreaped is DEAD to the runtime)
        state, starttime = _proc_stat(c["pid"])
        return (c["starttime"] is not None
                and starttime == c["starttime"]
                and state not in ("Z", "X", None))

    def pid(self, pod_key: str, name: str) -> Optional[int]:
        with self._mu:
            c = self._ctrs.get((pod_key, name))
            return None if c is None else c["pid"]

    def alive(self, pod_key: str, name: str) -> bool:
        with self._mu:
            c = self._ctrs.get((pod_key, name))
            return c is not None and self._alive_locked(c)

    def exit_code(self, pod_key: str, name: str) -> Optional[int]:
        """None while running (or unknown — adopted containers have no
        waitable status, like a runtime that lost the wait); the real
        wait status once dead.  A kill by signal N reports 128+N like a
        shell would."""
        with self._mu:
            c = self._ctrs.get((pod_key, name))
            if c is None or c["proc"] is None:
                return None
            rc = c["proc"].poll()
            if rc is None:
                return None
            return 128 - rc if rc < 0 else rc

    # -- restart recovery ----------------------------------------------------
    def adopt_checkpoints(self) -> int:
        """Scan the root for checkpoints of still-live processes and take
        them over (dockershim checkpoint recovery: a restarted kubelet
        resumes managing running containers).  Stale checkpoints (dead or
        reused pids) are deleted.  Returns how many were adopted."""
        adopted = 0
        try:
            pod_dirs = os.listdir(self.root)
        except OSError:
            return 0
        for pd in pod_dirs:
            cdir = os.path.join(self.root, pd, "containers")
            if not os.path.isdir(cdir):
                continue
            for cname in os.listdir(cdir):
                cp = os.path.join(cdir, cname, "checkpoint.json")
                try:
                    with open(cp) as f:
                        doc = json.load(f)
                    key = (doc.get("pod", ""), doc.get("name", ""))
                    pid = int(doc.get("pid", 0))
                    starttime = doc.get("starttime")
                except (OSError, ValueError, TypeError, AttributeError):
                    # a corrupt checkpoint degrades adoption for that
                    # container only — it must never stop the kubelet
                    try:
                        os.unlink(cp)
                    except OSError:
                        pass
                    continue
                state, cur_start = _proc_stat(pid) if pid > 0 else (None, None)
                live = (pid > 0 and starttime is not None
                        and cur_start == starttime
                        and state not in ("Z", "X", None))
                with self._mu:
                    if not live or key in self._ctrs:
                        if not live:
                            try:
                                os.unlink(cp)
                            except OSError:
                                pass
                        continue
                    self._ctrs[key] = {
                        "proc": None, "pid": pid, "starttime": starttime,
                        "rootfs": os.path.join(cdir, cname, "rootfs"),
                        "env": dict(doc.get("env") or {}),
                        "log": os.path.join(cdir, cname, "log"),
                        "command": list(doc.get("command") or []),
                    }
                    self.stats["adopted"] += 1
                    adopted += 1
        return adopted

    def stop(self, pod_key: str, name: str, timeout: float = 5.0) -> None:
        import time as _time

        with self._mu:
            c = self._ctrs.get((pod_key, name))
            live = c is not None and self._alive_locked(c)
        if c is None or not live:
            return
        proc, pid = c["proc"], c["pid"]

        def _wait(t: float) -> bool:
            if proc is not None:
                try:
                    proc.wait(timeout=t)
                    return True
                except subprocess.TimeoutExpired:
                    return False
            deadline = _time.monotonic() + t  # adopted: poll /proc
            while _time.monotonic() < deadline:
                state, starttime = _proc_stat(pid)
                # starttime change = gone/reused; Z/X = dead-but-unreaped
                # (a zombie must not stall the sweep for the full timeout)
                if starttime != c["starttime"] or state in ("Z", "X", None):
                    return True
                _time.sleep(0.02)
            return False

        try:  # signal the whole process group (shell + children)
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                return
        if not _wait(timeout):
            try:
                os.killpg(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    return
            _wait(timeout)  # D-state straggler; never block the sweep

    def remove(self, pod_key: str, name: str) -> None:
        self.stop(pod_key, name)
        with self._mu:
            self._ctrs.pop((pod_key, name), None)
        try:
            os.unlink(self.checkpoint_path(pod_key, name))
        except OSError:
            pass

    def remove_pod(self, pod_key: str) -> None:
        with self._mu:
            names = [n for (k, n) in self._ctrs if k == pod_key]
        for n in names:
            self.remove(pod_key, n)
        shutil.rmtree(self.pod_dir(pod_key), ignore_errors=True)

    def remove_all(self) -> None:
        with self._mu:
            keys = list(self._ctrs)
        for k, n in keys:
            self.remove(k, n)
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)
        _LIVE_MANAGERS.discard(self)

    def known_pods(self) -> set[str]:
        with self._mu:
            return {k for (k, _) in self._ctrs}

    # -- exec ---------------------------------------------------------------
    def exec_sync(self, pod_key: str, name: str, command: list[str],
                  timeout: float = 10.0) -> tuple[str, int]:
        """CRI ExecSync: run ``command`` in the container's context
        (rootfs cwd, container env).  Like the reference, exec into a
        dead container is an error (ValueError -> the server's 4xx)."""
        with self._mu:
            c = self._ctrs.get((pod_key, name))
            if c is None or not self._alive_locked(c):
                raise ValueError(f"container {pod_key}/{name} is not running")
            rootfs, env = c["rootfs"], dict(c["env"])
        full_env = dict(os.environ)
        full_env.update(env)
        full_env["KTPU_POD"] = pod_key
        full_env["KTPU_CONTAINER"] = name
        full_env["KTPU_ROOTFS"] = rootfs
        try:
            res = subprocess.run(
                command, cwd=rootfs, env=full_env, stdin=subprocess.DEVNULL,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return ("exec timed out", 124)
        except (FileNotFoundError, PermissionError) as e:
            return (str(e), 126)
        return (res.stdout.decode(errors="replace"), res.returncode)

    # -- observed usage (the cadvisor slice: /proc is the source) ------------
    def usage(self, pod_key: str) -> dict:
        """Kernel-observed usage summed over the pod's live container
        processes: RSS bytes (``/proc/<pid>/status`` VmRSS) and
        cumulative CPU milliseconds (``/proc/<pid>/stat`` utime+stime).
        The stats-summary endpoint serves this; a metrics client turns
        the cumulative CPU into a rate by sampling twice."""
        with self._mu:
            pids = [c["pid"] for (k, _), c in self._ctrs.items()
                    if k == pod_key and self._alive_locked(c)]
        rss = 0
        cpu_ms = 0.0
        tick = os.sysconf("SC_CLK_TCK") or 100
        for pid in pids:
            try:
                with open(f"/proc/{pid}/status") as f:
                    for line in f:
                        if line.startswith("VmRSS:"):
                            rss += int(line.split()[1]) * 1024
                            break
                with open(f"/proc/{pid}/stat") as f:
                    fields = f.read().rsplit(")", 1)[1].split()
                    # utime=field 14, stime=15 (1-indexed); after ')' the
                    # split starts at field 3
                    cpu_ms += (int(fields[11]) + int(fields[12])) / tick * 1000.0
            except (OSError, IndexError, ValueError):
                continue  # raced a death; skip
        return {"memoryBytes": rss, "cpuMillis": cpu_ms}

    def read_log(self, pod_key: str, name: str) -> Optional[list[str]]:
        path = self.log_path(pod_key, name)
        try:
            with open(path, "rb") as f:
                text = f.read().decode(errors="replace")
        except FileNotFoundError:
            return None
        return text.splitlines()
