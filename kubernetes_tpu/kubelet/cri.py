"""CRI: the Container Runtime Interface seam.

Capability of the reference's CRI layer (``pkg/kubelet/apis/cri/
services.go`` RuntimeService/ImageService, the ``v1alpha1/runtime``
gRPC proto, and ``pkg/kubelet/remote`` — the client the kubelet dials a
runtime daemon with).  Three pieces:

- :class:`RuntimeService` / :class:`ImageService` — the interface the
  kubelet programs containers through, runtime-agnostic.
- :class:`LocalCRI` — in-process implementation over the scriptable
  FakeRuntime + (optionally) real pause sandboxes: the dockershim slot.
- :class:`CRIServer` + :class:`RemoteCRI` — the same interface served
  over HTTP and dialed remotely (the ``remote/`` gRPC analogue), so a
  runtime can live in its own process exactly like dockerd did.
"""

from __future__ import annotations

import json
import threading
from typing import Optional


class RuntimeService:
    """``cri/services.go`` RuntimeService (sandbox + container halves)."""

    def run_pod_sandbox(self, pod_key: str) -> str:
        raise NotImplementedError

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        raise NotImplementedError

    def create_container(self, sandbox_id: str, name: str, image: str,
                         command: Optional[list[str]] = None,
                         env: Optional[dict] = None) -> str:
        raise NotImplementedError

    def start_container(self, container_id: str) -> None:
        raise NotImplementedError

    def stop_container(self, container_id: str) -> None:
        raise NotImplementedError

    def list_containers(self, sandbox_id: Optional[str] = None) -> list[dict]:
        raise NotImplementedError

    def exec_sync(self, container_id: str, command: list[str]) -> tuple[str, int]:
        raise NotImplementedError


class ImageService:
    """``cri/services.go`` ImageService."""

    def pull_image(self, image: str) -> str:
        raise NotImplementedError

    def list_images(self) -> list[str]:
        raise NotImplementedError

    def remove_image(self, image: str) -> None:
        raise NotImplementedError


class LocalCRI(RuntimeService, ImageService):
    """In-process runtime over FakeRuntime state (+ real pause processes
    when a sandbox manager is supplied, + REAL container processes when a
    ProcessContainerManager is supplied) — the dockershim of this stack.

    With ``processes`` set, CreateContainer records the spec,
    StartContainer forks the actual child (fork/exec), StopContainer
    signals it, ExecSync runs a real command in its context, and
    ListContainers reports kernel-observed state + pid."""

    def __init__(self, runtime=None, sandboxes=None, processes=None):
        from .runtime import FakeRuntime

        self.runtime = runtime or FakeRuntime()
        self.sandboxes = sandboxes  # ProcessSandboxManager | None
        self.processes = processes  # ProcessContainerManager | None
        self._mu = threading.Lock()
        self._containers: dict[str, dict] = {}  # id -> {sandbox,name,image,state}
        self._images: set[str] = set()
        self._next = 0

    def _new_id(self, prefix: str) -> str:
        self._next += 1
        return f"{prefix}-{self._next:06d}"

    # -- RuntimeService ----------------------------------------------------
    def run_pod_sandbox(self, pod_key: str) -> str:
        with self._mu:
            if self.sandboxes is not None:
                self.sandboxes.create(pod_key)
            return pod_key  # sandbox id IS the pod key at this depth

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        # signal/wait OUTSIDE the lock: a container trapping SIGTERM can
        # hold the graceful-stop wait for seconds, and every other CRI
        # RPC serializes on _mu
        if self.sandboxes is not None:
            self.sandboxes.remove(sandbox_id)
        if self.processes is not None:
            # containers die with their sandbox (kuberuntime stops
            # workload containers before the sandbox)
            self.processes.remove_pod(sandbox_id)
        with self._mu:
            for cid, c in list(self._containers.items()):
                if c["sandbox"] == sandbox_id:
                    c["state"] = "exited"

    def create_container(self, sandbox_id: str, name: str, image: str,
                         command=None, env=None) -> str:
        with self._mu:
            if image not in self._images:
                raise ValueError(f"image {image!r} not pulled")
            cid = self._new_id("ctr")
            self._containers[cid] = {"sandbox": sandbox_id, "name": name,
                                     "image": image, "state": "created",
                                     "command": list(command or []),
                                     "env": dict(env or {})}
            return cid

    def start_container(self, container_id: str) -> None:
        with self._mu:
            c = self._containers.get(container_id)
            if c is None or c["state"] == "exited":
                raise ValueError(f"cannot start {container_id}")
            if self.processes is not None:
                pid = self.processes.start(
                    c["sandbox"], c["name"],
                    command=c["command"] or None, env=c["env"])
                c["pid"] = pid
            c["state"] = "running"

    def stop_container(self, container_id: str) -> None:
        with self._mu:
            c = self._containers.get(container_id)
            ident = None if c is None else (c["sandbox"], c["name"])
        if ident is not None and self.processes is not None:
            self.processes.stop(*ident)  # TERM/KILL wait outside the lock
        with self._mu:
            c = self._containers.get(container_id)
            if c is not None:
                c["state"] = "exited"

    def list_containers(self, sandbox_id=None) -> list[dict]:
        with self._mu:
            out = []
            for cid, c in self._containers.items():
                if sandbox_id is not None and c["sandbox"] != sandbox_id:
                    continue
                entry = {"id": cid, **c}
                if self.processes is not None and c["state"] == "running":
                    # kernel truth outranks the ledger: a dead process IS
                    # an exited container, however it died.  The exit code
                    # persists in the ledger so pollers that miss the
                    # transition still learn it.
                    if not self.processes.alive(c["sandbox"], c["name"]):
                        c["state"] = "exited"
                        c["exitCode"] = self.processes.exit_code(
                            c["sandbox"], c["name"])
                        entry = {"id": cid, **c}
                out.append(entry)
            return out

    def exec_sync(self, container_id: str, command: list[str]) -> tuple[str, int]:
        with self._mu:
            c = self._containers.get(container_id)
            if c is None or c["state"] != "running":
                raise ValueError(f"container {container_id} not running")
            sandbox, name = c["sandbox"], c["name"]
        if self.processes is not None:
            return self.processes.exec_sync(sandbox, name, command)
        return self.runtime.exec(sandbox, name, command)

    # -- ImageService ------------------------------------------------------
    def pull_image(self, image: str) -> str:
        with self._mu:
            self._images.add(image)
            return image

    def list_images(self) -> list[str]:
        with self._mu:
            return sorted(self._images)

    def remove_image(self, image: str) -> None:
        with self._mu:
            self._images.discard(image)


_METHODS = {
    "RunPodSandbox": ("run_pod_sandbox", ["pod_key"]),
    "StopPodSandbox": ("stop_pod_sandbox", ["sandbox_id"]),
    "CreateContainer": ("create_container", ["sandbox_id", "name", "image",
                                             "command", "env"]),
    "StartContainer": ("start_container", ["container_id"]),
    "StopContainer": ("stop_container", ["container_id"]),
    "ListContainers": ("list_containers", ["sandbox_id"]),
    "ExecSync": ("exec_sync", ["container_id", "command"]),
    "PullImage": ("pull_image", ["image"]),
    "ListImages": ("list_images", []),
    "RemoveImage": ("remove_image", ["image"]),
}


class CRIServer:
    """Serves a RuntimeService+ImageService over HTTP (one POST per RPC —
    the ``v1alpha1/runtime`` gRPC surface's transport analogue)."""

    def __init__(self, cri: LocalCRI, host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self
        self.cri = cri

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                method = self.path.strip("/")
                spec = _METHODS.get(method)
                length = int(self.headers.get("Content-Length", 0))
                try:
                    params = json.loads(self.rfile.read(length)) if length else {}
                except ValueError:
                    return self._reply(400, {"error": "bad json"})
                if spec is None:
                    return self._reply(404, {"error": f"no method {method}"})
                fn_name, arg_names = spec
                try:
                    out = getattr(outer.cri, fn_name)(
                        *[params.get(a) for a in arg_names])
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    return self._reply(500, {"error": str(e)})
                return self._reply(200, {"result": out})

            def _reply(self, code, obj):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_port
        self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.httpd.server_address[0]}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()


class RemoteCRI(RuntimeService, ImageService):
    """Dials a CRIServer (``pkg/kubelet/remote`` RemoteRuntimeService)."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _call(self, method: str, **params):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"{self.url}/{method}", data=json.dumps(params).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read()).get("result")
        except urllib.error.HTTPError as e:
            raise ValueError(json.loads(e.read()).get("error", "CRI error"))

    def run_pod_sandbox(self, pod_key):
        return self._call("RunPodSandbox", pod_key=pod_key)

    def stop_pod_sandbox(self, sandbox_id):
        return self._call("StopPodSandbox", sandbox_id=sandbox_id)

    def create_container(self, sandbox_id, name, image, command=None, env=None):
        return self._call("CreateContainer", sandbox_id=sandbox_id,
                          name=name, image=image, command=command, env=env)

    def start_container(self, container_id):
        return self._call("StartContainer", container_id=container_id)

    def stop_container(self, container_id):
        return self._call("StopContainer", container_id=container_id)

    def list_containers(self, sandbox_id=None):
        return self._call("ListContainers", sandbox_id=sandbox_id)

    def exec_sync(self, container_id, command):
        out = self._call("ExecSync", container_id=container_id, command=command)
        return tuple(out)

    def pull_image(self, image):
        return self._call("PullImage", image=image)

    def list_images(self):
        return self._call("ListImages")

    def remove_image(self, image):
        return self._call("RemoveImage", image=image)
