"""Hollow kubelet: the node agent with a fake runtime.

Capability of the reference's kubemark HollowKubelet
(``pkg/kubemark/hollow_kubelet.go:48`` — real kubelet wiring over a fake
Docker client; SURVEY.md §4.5): register the node, heartbeat its Ready
condition, watch for pods bound to it, "start" them after a configurable
latency, and report pod/node status back — everything the control plane
observes from a node, with no containers underneath.  A fleet of these is
how 5k-node control-plane behavior is tested on one machine.

Scale shape: the fleet shares ONE pod informer with a by-node index (the
apiserver-side fieldSelector ``spec.nodeName=X`` the real kubelet uses),
so a tick is O(own pods), not O(cluster pods).

Tick-driven with an injected clock (the kubelet's syncLoop ticks,
``kubelet.go:1709``, collapsed into an explicit ``tick()``)."""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..api import types as api
from ..api.meta import ObjectMeta
from ..client.clientset import Clientset
from ..client.informer import PodNodeIndex, SharedInformer
from ..store.store import AlreadyExistsError, ConflictError, NotFoundError


class HollowKubelet:
    def __init__(
        self,
        clientset: Clientset,
        node_name: str,
        pod_index: Optional[PodNodeIndex] = None,
        cpu: str = "8",
        memory: str = "16Gi",
        pods: int = 110,
        labels: Optional[dict] = None,
        pod_start_latency: float = 0.5,
        heartbeat_interval: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.clientset = clientset
        self.node_name = node_name
        self.pod_index = pod_index
        self.cpu = cpu
        self.memory = memory
        self.pods = pods
        self.labels = labels or {}
        self.pod_start_latency = pod_start_latency
        self.heartbeat_interval = heartbeat_interval
        self._clock = clock
        self._last_heartbeat = -1e18
        self._starting: dict[str, float] = {}  # pod key -> bind-seen time

    # -- registration (kubelet_node_status.go registerWithApiserver) -------
    def register(self) -> None:
        labels = dict(self.labels)
        labels.setdefault(api.HOSTNAME_LABEL, self.node_name)
        node = api.Node(
            meta=ObjectMeta(name=self.node_name, namespace="", labels=labels),
            status=api.NodeStatus(
                capacity={
                    api.CPU: api.Quantity(self.cpu),
                    api.MEMORY: api.Quantity(self.memory),
                    api.PODS: api.Quantity(self.pods),
                },
                allocatable={
                    api.CPU: api.Quantity(self.cpu),
                    api.MEMORY: api.Quantity(self.memory),
                    api.PODS: api.Quantity(self.pods),
                },
                conditions=[
                    api.NodeCondition(
                        type=api.NODE_READY, status="True", heartbeat_time=self._clock()
                    )
                ],
            ),
        )
        try:
            self.clientset.nodes.create(node)
        except AlreadyExistsError:
            self._heartbeat(force=True)

    def _my_pods(self) -> list[api.Pod]:
        if self.pod_index is not None:
            return self.pod_index.pods_on(self.node_name)
        return [
            p for p in self.clientset.pods.list()[0] if p.spec.node_name == self.node_name
        ]

    # -- the sync tick -----------------------------------------------------
    def tick(self) -> dict:
        """One syncLoop iteration: heartbeat if due, admit newly-bound pods,
        transition starting pods to Running after the start latency."""
        now = self._clock()
        out = {"started": 0, "observed": 0}
        self._heartbeat()

        mine = self._my_pods()
        live = {p.meta.key for p in mine}
        for pod in mine:
            if pod.status.phase != api.PENDING:
                continue
            key = pod.meta.key
            if key not in self._starting:
                self._starting[key] = now
                out["observed"] += 1
            elif now - self._starting[key] >= self.pod_start_latency:
                if self._set_running(pod, now):
                    out["started"] += 1
                del self._starting[key]
        self._starting = {k: t for k, t in self._starting.items() if k in live}
        return out

    def _set_running(self, pod: api.Pod, now: float) -> bool:
        # pod may be a shared informer-cache object (PodNodeIndex path):
        # never mutate it — build the status update on a private copy
        update = api.Pod.from_dict(pod.to_dict())
        update.status.phase = api.RUNNING
        update.status.host_ip = self.node_name
        try:
            self.clientset.pods.update_status(update)
            return True
        except (NotFoundError, ConflictError):
            return False

    def _heartbeat(self, force: bool = False) -> None:
        now = self._clock()
        if not force and now - self._last_heartbeat < self.heartbeat_interval:
            return
        self._last_heartbeat = now

        def _mutate(cur: api.Node) -> api.Node:
            c = cur.status.condition(api.NODE_READY)
            if c is None:
                c = api.NodeCondition(type=api.NODE_READY)
                cur.status.conditions.append(c)
            c.status = "True"
            c.heartbeat_time = now
            c.heartbeat_revision = cur.meta.resource_version
            return cur

        try:
            self.clientset.nodes.guaranteed_update(self.node_name, _mutate, "")
        except NotFoundError:
            self.register()


class HollowFleet:
    """N hollow kubelets against one control plane (start-kubemark.sh),
    sharing one pod informer + by-node index."""

    def __init__(
        self,
        clientset: Clientset,
        n: int,
        clock: Callable[[], float] = time.monotonic,
        **kubelet_kw,
    ):
        self.informer = SharedInformer(clientset.pods)
        self.index = PodNodeIndex(self.informer)
        self.kubelets = [
            HollowKubelet(
                clientset, f"hollow-{i:05d}", pod_index=self.index, clock=clock, **kubelet_kw
            )
            for i in range(n)
        ]

    def register_all(self) -> None:
        for k in self.kubelets:
            k.register()
        self.informer.start_manual()

    def tick_all(self) -> dict:
        self.informer.pump()
        total = {"started": 0, "observed": 0}
        for k in self.kubelets:
            r = k.tick()
            total["started"] += r["started"]
            total["observed"] += r["observed"]
        return total
