"""Hollow kubelet: the node agent with a fake runtime.

Capability of the reference's kubemark HollowKubelet
(``pkg/kubemark/hollow_kubelet.go:48`` — real kubelet wiring over a fake
Docker client; SURVEY.md §4.5): register the node, heartbeat its Ready
condition, watch for pods bound to it, "start" them after a configurable
latency, and report pod/node status back — everything the control plane
observes from a node, with no containers underneath.  A fleet of these is
how 5k-node control-plane behavior is tested on one machine.

Scale shape: the fleet shares ONE pod informer with a by-node index (the
apiserver-side fieldSelector ``spec.nodeName=X`` the real kubelet uses),
so a tick is O(own pods), not O(cluster pods).

Tick-driven with an injected clock (the kubelet's syncLoop ticks,
``kubelet.go:1709``, collapsed into an explicit ``tick()``)."""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.kubelet")

from ..api import types as api
from ..api.meta import ObjectMeta
from ..client.clientset import Clientset
from ..utils.features import DEFAULT_FEATURE_GATES
from ..client.informer import PodNodeIndex, SharedInformer
from ..store.store import AlreadyExistsError, ConflictError, NotFoundError
from .cm import AdmissionRejected


class HollowKubelet:
    def __init__(
        self,
        clientset: Clientset,
        node_name: str,
        pod_index: Optional[PodNodeIndex] = None,
        cpu: str = "8",
        memory: str = "16Gi",
        pods: int = 110,
        labels: Optional[dict] = None,
        pod_start_latency: float = 0.5,
        heartbeat_interval: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        runtime: "FakeRuntime" = None,
        memory_pressure_fraction: float = 0.95,
        serve: bool = False,
        mount_latency: float = 0.0,
        real_sandboxes: bool = False,
        real_containers: bool = False,
        container_root: Optional[str] = None,
        static_pod_dir: Optional[str] = None,
        manifest_url: Optional[str] = None,
        system_reserved_cpu: str = "0",
        system_reserved_memory: str = "0",
        kube_reserved_cpu: str = "0",
        kube_reserved_memory: str = "0",
    ):
        from .runtime import FakeRuntime, PodRuntimeManager

        self.clientset = clientset
        self.node_name = node_name
        self.pod_index = pod_index
        self.cpu = cpu
        self.memory = memory
        self.pods = pods
        self.labels = labels or {}
        self.pod_start_latency = pod_start_latency
        self.heartbeat_interval = heartbeat_interval
        self._clock = clock
        self._last_heartbeat = -1e18
        self._starting: dict[str, float] = {}  # pod key -> bind-seen time
        # probe / restart / eviction machinery (pkg/kubelet prober +
        # eviction manager over a scriptable fake runtime)
        self.runtime = runtime or FakeRuntime()
        # optional REAL per-pod sandbox processes (csrc/pause.c, the
        # reference's pause container): a pause process runs exactly
        # while the pod is Running; teardown on termination or removal
        self.sandboxes = None
        if real_sandboxes or real_containers:
            from .runtime import ProcessSandboxManager

            mgr = ProcessSandboxManager()
            self.sandboxes = mgr if mgr.enabled else None
        # optional REAL containers: forked child processes with on-disk
        # volumes (kubelet/containers.py + volumehost.py) — exec, logs
        # and cp then operate on actual processes/files
        self.containers = None
        self.volume_host = None
        if real_containers:
            from .containers import ProcessContainerManager
            from .volumehost import VolumeHost

            self.containers = ProcessContainerManager(root=container_root)
            if container_root is not None:
                # restart recovery: adopt still-live containers from the
                # previous kubelet process's checkpoints (dockershim
                # checkpoint_store) instead of orphaning them
                self.containers.adopt_checkpoints()
            self.volume_host = VolumeHost(
                fetch_configmap=self._fetch_configmap,
                fetch_secret=self._fetch_secret,
            )
            self.runtime.exec_delegate = self.containers.exec_sync
            self.runtime.log_delegate = self.containers.read_log
            self.runtime.file_read_delegate = self._read_rootfs_file
            self.runtime.file_write_delegate = self._write_rootfs_file
        self.pod_manager = PodRuntimeManager(
            self.runtime, clock,
            containers=self.containers, volume_host=self.volume_host)
        # static pods (pkg/kubelet/config file source + mirror pods):
        # manifests in this directory run on the node WITHOUT a scheduler
        # — how kubeadm self-hosts the control plane.  The kubelet
        # mirrors them into the API for visibility; the FILE is the
        # source of truth (API deletion of a mirror is undone next tick).
        self.static_pod_dir = static_pod_dir
        # the http pod source (config/http.go): one URL serving a single
        # pod manifest, merged with the file source through the same
        # static-pod machinery; polled at its own cadence (the
        # reference's --http-check-frequency), never per tick
        self.manifest_url = manifest_url
        self.http_check_frequency = 20.0
        self._last_url_fetch = -1e18
        self._last_url_body: Optional[bytes] = None
        self._static_seen: dict[str, tuple[str, str]] = {}  # source -> (content hash, pod key)
        from .cm import ContainerManager, ImageManager
        from .pleg import PLEG

        # resource accounting: the cgroup-analogue tree + node admission
        # (pkg/kubelet/cm) and image GC (pkg/kubelet/images)
        self.cm = ContainerManager(
            cpu, memory, pods,
            system_reserved_cpu=system_reserved_cpu,
            system_reserved_memory=system_reserved_memory,
            kube_reserved_cpu=kube_reserved_cpu,
            kube_reserved_memory=kube_reserved_memory,
        )
        self.images = ImageManager(clock=clock)
        self.image_gc_period = 30.0
        self._last_image_gc = -1e18
        # relist-based lifecycle events (pleg/generic.go:181): out-of-band
        # runtime changes surface within one relist period
        self.pleg = PLEG(self.pod_manager, self.sandboxes, clock=clock)
        # pod networking through the plugin seam (pkg/kubelet/network):
        # constructed lazily at first setup so the node's ALLOCATED
        # podCIDR (written by the IPAM controller after registration) is
        # respected
        self.network = None
        from .volumemanager import VolumeManager

        self.volume_manager = VolumeManager(clock, mount_latency=mount_latency)
        self._last_in_use: list[str] = []
        self.memory_pressure_fraction = memory_pressure_fraction
        self._memory_capacity = api.Quantity(memory).value()
        # the node's read API (pkg/kubelet/server): logs/pods/healthz
        self.server = None
        if serve:
            from .server import KubeletServer
            from ..auth.authn import kubelet_exec_token

            self.server = KubeletServer(self, exec_token=kubelet_exec_token(node_name))
            self.server.start()

    # -- real-container plumbing -------------------------------------------
    def _fetch_configmap(self, ns: str, name: str):
        try:
            return self.clientset.client_for("ConfigMap").get(name, ns).data
        except Exception as e:  # noqa: BLE001 — missing source: keep last payload
            logger.debug("%s: configmap %s/%s unavailable (%s); keeping "
                         "last payload", self.node_name, ns, name,
                         type(e).__name__)
            return None

    def _fetch_secret(self, ns: str, name: str):
        try:
            return self.clientset.client_for("Secret").get(name, ns).data
        except Exception as e:  # noqa: BLE001 — missing source: keep last payload
            logger.debug("%s: secret %s/%s unavailable (%s); keeping last "
                         "payload", self.node_name, ns, name,
                         type(e).__name__)
            return None

    def _rootfs_path(self, pod_key: str, container: str, path: str):
        """Resolve a cp path inside the container's real rootfs; None for
        escapes (.. traversal must not reach the host)."""
        import os

        rootfs = self.containers.rootfs(pod_key, container)
        full = os.path.normpath(os.path.join(rootfs, path.lstrip("/")))
        # separator-anchored: "../rootfs-evil/x" normalizes to a SIBLING
        # whose name merely starts with "rootfs" and must not pass
        if full != rootfs and not full.startswith(rootfs + os.sep):
            return None
        return full

    def _read_rootfs_file(self, pod_key: str, container: str, path: str):
        full = self._rootfs_path(pod_key, container, path)
        if full is None:
            return None
        try:
            with open(full, "rb") as f:
                return f.read()
        except OSError:
            return None

    def _write_rootfs_file(self, pod_key: str, container: str, path: str,
                           data: bytes) -> bool:
        import os

        full = self._rootfs_path(pod_key, container, path)
        if full is None:
            return False
        try:
            os.makedirs(os.path.dirname(full), exist_ok=True)
            with open(full, "wb") as f:
                f.write(data)
            return True
        except OSError:
            return False

    # -- stats (pkg/kubelet/server/stats/summary.go) -----------------------
    def stats_summary(self) -> dict:
        """The kubelet stats-summary document the metrics pipeline
        scrapes (HPA metrics client, ``kubectl top``).  Real containers
        report kernel-observed RSS + cumulative CPU from /proc; hollow
        pods report the scripted cadvisor signal."""
        scripted = self.runtime.pod_memory_usage
        pods = []
        for p in self._my_pods():
            key = p.meta.key
            entry = {
                "podRef": {"namespace": p.meta.namespace, "name": p.meta.name},
                "memory": {"usageBytes": scripted.get(key, 0)},
            }
            if self.containers is not None:
                u = self.containers.usage(key)
                if u["memoryBytes"] or u["cpuMillis"]:
                    entry["memory"] = {"usageBytes": u["memoryBytes"]}
                    entry["cpu"] = {"cumulativeCpuMillis": u["cpuMillis"]}
            pods.append(entry)
        return {"node": {"nodeName": self.node_name}, "pods": pods}

    # -- registration (kubelet_node_status.go registerWithApiserver) -------
    def register(self) -> None:
        labels = dict(self.labels)
        labels.setdefault(api.HOSTNAME_LABEL, self.node_name)
        kubelet_url = self.server.url if self.server is not None else ""
        node = api.Node(
            meta=ObjectMeta(name=self.node_name, namespace="", labels=labels),
            status=api.NodeStatus(
                capacity={
                    api.CPU: api.Quantity(self.cpu),
                    api.MEMORY: api.Quantity(self.memory),
                    api.PODS: api.Quantity(self.pods),
                },
                # allocatable = capacity − system-reserved − kube-reserved
                # (container_manager_linux.go GetNodeAllocatable) — what
                # the scheduler budgets against
                allocatable={
                    api.CPU: api.Quantity(f"{self.cm.allocatable_cpu}m"),
                    api.MEMORY: api.Quantity(str(self.cm.allocatable_memory)),
                    api.PODS: api.Quantity(self.pods),
                },
                conditions=[
                    api.NodeCondition(
                        type=api.NODE_READY, status="True", heartbeat_time=self._clock()
                    )
                ],
                kubelet_url=kubelet_url,
            ),
        )
        try:
            self.clientset.nodes.create(node)
        except AlreadyExistsError:
            self._heartbeat(force=True)

    def _my_pods(self) -> list[api.Pod]:
        if self.pod_index is not None:
            return self.pod_index.pods_on(self.node_name)
        store = self.clientset.store
        if getattr(store, "base_url", None) is not None:
            # remote node: server-side fieldSelector (the real kubelet's
            # spec.nodeName= list) — never pull the whole cluster per node
            items, _ = store.list("Pod", None,
                                  field_selector=f"spec.nodeName={self.node_name}")
            return [api.Pod.from_dict(d) for d in items]
        return [
            p for p in self.clientset.pods.list()[0] if p.spec.node_name == self.node_name
        ]

    # -- static pods (pkg/kubelet/config file source + mirror pods) --------
    def _sync_static_pods(self, existing_keys: set) -> bool:
        """Manifests from ``static_pod_dir`` and/or ``manifest_url`` run
        on this node without a scheduler (how kubeadm self-hosts the
        control plane): each one becomes a pod named ``<name>-<node>``
        bound here and MIRRORED into the API
        (``kubernetes.io/config.mirror``) for visibility.  The source is
        the truth — edits recreate the pod (change detection by CONTENT
        hash, never mtime: the reference hashes the manifest, and mtime
        granularity would miss same-second rewrites), removal stops it,
        and a deleted mirror is re-created.  ``existing_keys`` is this
        tick's node pod listing, so steady state costs no extra API
        reads.  Returns True when anything changed (the caller refetches
        its pod list)."""
        import hashlib
        import logging

        log = logging.getLogger("kubernetes_tpu.kubelet")
        present: dict[str, tuple[str, str]] = {}  # source -> (content hash, key)
        changed = False
        # sources: every manifest file in the dir, plus the manifest URL
        # (config/file.go + config/http.go merged into one update stream)
        sources: list[tuple[str, Optional[bytes]]] = []
        if self.static_pod_dir is not None:
            dir_sources = self._static_dir_sources()
            if dir_sources is None:
                # a transiently unreadable DIR must not read as "every
                # manifest removed": carry all previously-seen file
                # sources unchanged (same contract as a per-file race)
                sources.extend(
                    (p, None) for p in self._static_seen
                    if p != self.manifest_url)
            else:
                sources.extend(dir_sources)
        if self.manifest_url:
            # poll at http_check_frequency, not per tick: a slow or
            # blackholed URL must not stall probes/restarts every cycle
            now = self._clock()
            if now - self._last_url_fetch >= self.http_check_frequency:
                self._last_url_fetch = now
                import urllib.request

                try:
                    with urllib.request.urlopen(self.manifest_url,
                                                timeout=5) as r:
                        self._last_url_body = r.read()
                except Exception:  # noqa: BLE001 — an unreachable URL
                    # keeps the last incarnation, like an unreadable file
                    self._last_url_body = None
            sources.append((self.manifest_url, self._last_url_body))
        for path, raw in sources:
            prev = self._static_seen.get(path)
            if raw is None:
                if prev is not None:
                    present[path] = prev
                continue
            digest = hashlib.sha256(raw).hexdigest()
            if prev is not None and prev[0] == digest:
                if prev[1] in existing_keys:
                    present[path] = prev
                    continue
                # mirror deleted out from under us: the FILE outranks the
                # API — forget the runtime incarnation and recreate
                self.pod_manager.forget(prev[1])
                prev = None
            pod = self._parse_static_manifest(
                raw, "http" if path == self.manifest_url else "file",
                origin=path)
            if pod is None:
                if prev is not None:
                    present[path] = prev
                continue
            key = pod.meta.key
            if prev is not None and prev[1] != key:
                self._delete_mirror(prev[1])  # renamed in the file
                changed = True
            if prev is not None and prev[1] == key:
                # changed manifest: recreate with the new spec
                self._delete_mirror(key)
                self.pod_manager.forget(key)
            try:
                self.clientset.pods.create(pod)
                changed = True
            except AlreadyExistsError:
                # NEVER steal a non-mirror pod: a user pod that happens to
                # share the name keeps running and the manifest is skipped
                # (real mirror-pod handling verifies the annotation too)
                if not self._is_our_mirror(key):
                    log.warning(
                        "static pod %s collides with an existing non-static "
                        "pod; manifest %s skipped", key, path)
                    continue
                self._delete_mirror(key)
                self.pod_manager.forget(key)
                try:
                    self.clientset.pods.create(pod)
                    changed = True
                except AlreadyExistsError:
                    pass
            present[path] = (digest, key)
        for path, (_, key) in self._static_seen.items():
            if path not in present and key:
                self._delete_mirror(key)  # manifest removed
                changed = True
        self._static_seen = present
        return changed

    def _parse_static_manifest(self, raw: bytes, source: str,
                               origin: str = ""):
        """Manifest bytes -> the static pod with the reference identity
        (``<name>-<nodename>``, bound here, mirror annotations); None on
        a bad manifest (warned with the parse error — during self-hosted
        bootstrap these manifests ARE the control plane)."""
        import logging

        import yaml as _yaml

        try:
            pod = api.Pod.from_dict(_yaml.safe_load(raw.decode()))
            if not pod.meta.name:
                raise ValueError("manifest has no metadata.name")
        except Exception as e:  # noqa: BLE001 — a bad manifest must not
            # take down the sync loop
            logging.getLogger("kubernetes_tpu.kubelet").warning(
                "static pod manifest %s unreadable: %s", origin or source, e)
            return None
        pod.meta.name = f"{pod.meta.name}-{self.node_name}"
        pod.spec.node_name = self.node_name
        pod.meta.annotations["kubernetes.io/config.mirror"] = "true"
        pod.meta.annotations["kubernetes.io/config.source"] = source
        return pod

    def _static_dir_sources(self) -> list:
        """The file half of the static-pod source walk: every manifest
        file as ``(path, bytes | None)`` — None marks a transiently
        unreadable file (callers must carry the prior incarnation, never
        treat it as removed).  An unreadable DIR yields None so callers
        can apply the same carry-over rule to every known file source."""
        import os

        try:
            entries = sorted(os.listdir(self.static_pod_dir))
        except OSError:
            return None
        sources = []
        for fname in entries:
            if not fname.endswith((".yaml", ".yml", ".json")):
                continue
            path = os.path.join(self.static_pod_dir, fname)
            try:
                with open(path, "rb") as f:
                    sources.append((path, f.read()))
            except OSError:
                # a write-rename race or transient permission error must
                # not read as "manifest removed"
                sources.append((path, None))
        return sources

    def standalone_static_tick(self) -> int:
        """Static pods WITHOUT an apiserver: the kubeadm bootstrap state,
        where the control-plane kubelet must run its manifest dir (the
        apiserver's own pod included) before any API exists (reference
        kubelet standalone mode, ``config/file.go`` with no api source).
        Containers start through the same runtime manager the API path
        uses, so when the API comes up the mirror-pod flow ADOPTS the
        already-running processes instead of restarting them.  Returns
        how many manifests are being enforced."""
        if self.static_pod_dir is None:
            return 0
        n = 0
        for path, raw in (self._static_dir_sources() or []):
            if raw is None:
                continue
            pod = self._parse_static_manifest(raw, "file", origin=path)
            if pod is None:
                continue
            # sync_pod starts the containers and restarts dead ones per
            # restartPolicy — the standalone crash-loop that keeps the
            # apiserver container retrying until it binds its port
            self.pod_manager.sync_pod(pod)
            n += 1
        return n

    def _is_our_mirror(self, pod_key: str) -> bool:
        ns, name = pod_key.split("/", 1)
        try:
            cur = self.clientset.pods.get(name, ns)
        except NotFoundError:
            return False
        return (cur.meta.annotations.get("kubernetes.io/config.mirror") == "true"
                and cur.spec.node_name == self.node_name)

    def _delete_mirror(self, pod_key: str) -> None:
        ns, name = pod_key.split("/", 1)
        try:
            self.clientset.pods.delete(name, ns)
        except NotFoundError:
            pass

    # -- the sync tick -----------------------------------------------------
    def tick(self) -> dict:
        """One syncLoop iteration: heartbeat if due, admit newly-bound pods,
        transition starting pods to Running after the start latency, run
        probes/restarts, then the eviction manager pass."""
        now = self._clock()
        out = {"started": 0, "observed": 0, "restarts": 0, "evicted": 0}
        self._maybe_apply_dynamic_config()
        self._heartbeat()

        mine = self._my_pods()
        if self.static_pod_dir is not None or self.manifest_url:
            if self._sync_static_pods({p.meta.key for p in mine}):
                mine = self._my_pods()  # mirrors changed: refresh the view
        live = {p.meta.key for p in mine}
        # volume manager pass (reconciler.go:165): pods with PVC-backed
        # volumes may only start once attach + mount complete
        pvc_to_pv = self._pvc_to_pv(mine)
        if pvc_to_pv is not None or self.volume_manager.has_state():
            # the second arm: departed pods must still UNMOUNT (and clear
            # volumesInUse) even when no remaining pod needs volumes
            attached = self._attached_volumes()
            self.volume_manager.sync(mine, attached, pvc_to_pv or {})
            self._report_volumes_in_use()
        running: list[api.Pod] = []
        started_keys: set[str] = set()
        for pod in mine:
            if pod.status.phase == api.RUNNING:
                running.append(pod)
                continue
            if pod.status.phase != api.PENDING:
                continue
            key = pod.meta.key
            if key not in self._starting:
                # node-side admission over allocatable (the kubelet's
                # canAdmitPod backstop): a pod that does not fit is
                # REJECTED here regardless of the scheduler's view.
                # add_pod (not bare admit) so the requests RESERVE
                # immediately — N pods admitted in one tick must each see
                # the previous ones' debits, or they all pass
                try:
                    self.cm.add_pod(pod)
                except AdmissionRejected as e:
                    self._reject_pod(pod, e)
                    out["rejected"] = out.get("rejected", 0) + 1
                    continue
                self._starting[key] = now
                out["observed"] += 1
            elif now - self._starting[key] >= self.pod_start_latency:
                if pvc_to_pv is not None and not self.volume_manager.pod_volumes_ready(
                    pod, pvc_to_pv
                ):
                    continue  # WaitForAttachAndMount: stay Pending
                if self._set_running(pod, now):
                    out["started"] += 1
                    started_keys.add(key)
                    self.images.ensure_pulled(pod)
                del self._starting[key]
        self._starting = {k: t for k, t in self._starting.items() if k in live}

        out["restarts"], still_running = self._sync_running(running)
        for gone in self.pod_manager.known() - live:
            self.pod_manager.forget(gone)
        # resource-ledger hygiene: pods that left the runtime release
        # their cgroup + image references (admitted-but-starting pods
        # keep their reservation — that's the point of admitting early)
        running_now = {p.meta.key for p in still_running} | started_keys
        for gone in self.cm.known() - running_now - set(self._starting):
            self.cm.remove_pod(gone)
            self.images.release(gone)
        # CNI DEL: release address leases for departed pods so the range
        # recycles (a churning node must not exhaust its /24)
        if self.network is not None:
            for gone in self.network.leased() - running_now:
                self.network.teardown_pod(gone)
        # pods observed ALREADY running (kubelet restart recovery) join
        # the ledger without re-admission — and their existing addresses
        # are adopted into the network plugin so a fresh process cannot
        # lease a running pod's IP to a newcomer
        for pod in still_running:
            if pod.meta.key not in self.cm.known():
                self.cm.add_pod(pod, force=True)
                self.images.ensure_pulled(pod)
            if (pod.status.pod_ip and not pod.spec.host_network
                    and self._network().pod_ip(pod.meta.key) is None):
                self.network.adopt(pod.meta.key, pod.status.pod_ip)
        # PLEG relist: out-of-band sandbox deaths surface as events; a
        # Running pod whose pause process was killed behind our back gets
        # its sandbox restarted (kuberuntime SyncPod recreates the
        # sandbox when the runtime lost it)
        out["pleg_events"] = 0
        out["sandbox_restarts"] = 0
        for ev in self.pleg.relist():
            out["pleg_events"] += 1
            if ev.type == "SandboxDied" and ev.pod_key in running_now:
                if self.sandboxes is not None:
                    self.sandboxes.remove(ev.pod_key)  # reap the corpse
                    self.sandboxes.create(ev.pod_key)
                    out["sandbox_restarts"] += 1
        evicted_keys = self._eviction_pass(still_running)
        out["evicted"] = len(evicted_keys)
        for key in evicted_keys:
            self.cm.remove_pod(key)
            self.images.release(key)
            if self.network is not None:
                self.network.teardown_pod(key)
        # image GC at its own cadence; failure to reach the low target
        # raises the disk-pressure signal
        if now - self._last_image_gc >= self.image_gc_period:
            self._last_image_gc = now
            gc = self.images.garbage_collect()
            self._set_disk_pressure_condition(gc["over"])
        if self.sandboxes is not None:
            # sandboxes exist exactly while the pod is Running (incl. pods
            # started THIS tick, excl. pods evicted this tick): a pod that
            # went Succeeded/Failed/Evicted leaves the set and its pause
            # process is stopped NOW, not at object deletion (the
            # reference stops the sandbox on pod termination)
            running_keys = ({p.meta.key for p in still_running}
                            | started_keys) - evicted_keys
            for key in running_keys:
                self.sandboxes.create(key)
            for gone in self.sandboxes.known() - running_keys:
                self.sandboxes.remove(gone)
        return out

    def _sync_running(self, running: list[api.Pod]) -> tuple[int, list[api.Pod]]:
        """Prober + restart-policy pass; pushes status only on change.
        Returns pods still running — a pod that went terminal this tick
        must not be re-ranked by the eviction pass."""
        restarts = 0
        still_running: list[api.Pod] = []
        for pod in running:
            outcome, statuses, all_ready = self.pod_manager.sync_pod(pod)
            prev = pod.status
            new_restarts = sum(s.restart_count for s in statuses) - sum(
                s.restart_count for s in prev.container_statuses
            )
            restarts += max(0, new_restarts)
            phase = {
                "running": api.RUNNING,
                "succeeded": api.SUCCEEDED,
                "failed": api.FAILED,
            }[outcome]
            if outcome == "running":
                still_running.append(pod)
            else:
                self.pod_manager.forget(pod.meta.key)
            prev_ready = any(
                c.get("type") == "Ready" and c.get("status") == "True"
                for c in prev.conditions
            )
            changed = (
                phase != prev.phase
                or all_ready != prev_ready
                or [s.to_dict() for s in statuses]
                != [s.to_dict() for s in prev.container_statuses]
            )
            if not changed:
                continue
            update = api.Pod.from_dict(pod.to_dict())
            update.status.phase = phase
            update.status.container_statuses = statuses
            conds = [c for c in update.status.conditions if c.get("type") != "Ready"]
            conds.append({"type": "Ready", "status": "True" if all_ready else "False"})
            update.status.conditions = conds
            try:
                self.clientset.pods.update_status(update)
            except (NotFoundError, ConflictError):
                continue
        return restarts, still_running

    # tunables a ConfigMap may override (reference KubeletConfiguration
    # fields this hollow node actually consumes)
    _DYNAMIC_FIELDS = {
        "podStartLatency": ("pod_start_latency", float),
        "heartbeatInterval": ("heartbeat_interval", float),
        "memoryPressureFraction": ("memory_pressure_fraction", float),
    }

    def _maybe_apply_dynamic_config(self) -> None:
        """Dynamic kubelet config (reference ``kubelet/kubeletconfig``,
        gated by DynamicKubeletConfig): a ConfigMap named
        ``kubelet-config-<node>`` in kube-system overrides the node's
        tunables live; deleting it (or a field going invalid) rolls back
        to the boot values.  Polled at heartbeat cadence, never per tick
        — a 5k-node fleet must not turn the gate into 5k GETs/s."""
        if not DEFAULT_FEATURE_GATES.enabled("DynamicKubeletConfig"):
            return
        if not hasattr(self, "_boot_config"):
            self._boot_config = {attr: getattr(self, attr)
                                 for attr, _ in self._DYNAMIC_FIELDS.values()}
            self._config_rv = None
            self._last_config_check = None
        now = self._clock()
        # throttle on the BOOT heartbeat interval: a ConfigMap that raises
        # heartbeatInterval must not lock out its own rollback
        if (self._last_config_check is not None
                and now - self._last_config_check
                < self._boot_config["heartbeat_interval"]):
            return
        self._last_config_check = now
        try:
            cm = self.clientset.client_for("ConfigMap").get(
                f"kubelet-config-{self.node_name}", "kube-system")
        except NotFoundError:
            if self._config_rv is not None:
                # roll back ONLY when an override was actually applied —
                # never clobber harness-set attributes in the normal
                # no-ConfigMap fleet state
                for attr, value in self._boot_config.items():
                    setattr(self, attr, value)
                self._config_rv = None
            return
        rv = cm.meta.resource_version
        if rv == self._config_rv:
            return
        for key, (attr, cast) in self._DYNAMIC_FIELDS.items():
            raw = cm.data.get(key)
            if raw is None:
                setattr(self, attr, self._boot_config[attr])
                continue
            try:
                setattr(self, attr, cast(raw))
            except (TypeError, ValueError):
                # an invalid value must not keep a STALE prior override
                setattr(self, attr, self._boot_config[attr])
        self._config_rv = rv

    def _eviction_pass(self, running: list[api.Pod]) -> set:
        """eviction_manager.go:213 synchronize — memory signal vs the
        threshold; rank by QoS then usage; evict until under.  Returns the
        victims' keys so the caller's sandbox reconcile drops their pause
        processes the same tick.

        The signal is ACCOUNTED, not scripted: the cadvisor-feed sample
        (runtime.pod_memory_usage) is charged into each pod's cgroup and
        the decision reads the kubepods rollup (pkg/kubelet/cm)."""
        from .runtime import rank_for_eviction

        usage = self.runtime.pod_memory_usage
        self.cm.charge_usage(usage)
        used = self.cm.node_usage()
        threshold = self._memory_capacity * self.memory_pressure_fraction
        under_pressure = used > threshold
        self._set_pressure_condition(under_pressure)
        evicted: set = set()
        if not under_pressure:
            return evicted
        for victim in rank_for_eviction(running, usage):
            if used <= threshold:
                break
            update = api.Pod.from_dict(victim.to_dict())
            update.status.phase = api.FAILED
            update.status.reason = "Evicted"
            try:
                self.clientset.pods.update_status(update)
            except (NotFoundError, ConflictError):
                continue
            used -= usage.get(victim.meta.key, 0)
            self.pod_manager.forget(victim.meta.key)
            evicted.add(victim.meta.key)
        return evicted

    def _pvc_to_pv(self, mine: list[api.Pod]):
        """ns/claim -> bound PV name, or None when no pod needs volumes
        (skips the PVC list entirely — the common case)."""
        if not any(v.pvc_name for p in mine for v in p.spec.volumes):
            return None
        out = {}
        for pvc in self.clientset.persistentvolumeclaims.list(None)[0]:
            if pvc.volume_name:
                out[pvc.meta.key] = pvc.volume_name
        return out

    def _attached_volumes(self) -> set:
        try:
            node = self.clientset.nodes.get(self.node_name)
        except NotFoundError:
            return set()
        return set(node.status.volumes_attached)

    def _report_volumes_in_use(self) -> None:
        in_use = self.volume_manager.volumes_in_use()
        if in_use == self._last_in_use:
            return

        def _mutate(cur: api.Node) -> api.Node:
            cur.status.volumes_in_use = list(in_use)
            return cur

        try:
            self.clientset.nodes.guaranteed_update(self.node_name, _mutate, "")
            self._last_in_use = in_use
        except NotFoundError:
            pass

    def _reject_pod(self, pod: api.Pod, err) -> None:
        """kubelet admission failure: phase Failed, reason OutOf<res>
        (the reference's lifecycle.PodAdmitResult rejection path)."""
        update = api.Pod.from_dict(pod.to_dict())
        update.status.phase = api.FAILED
        update.status.reason = f"OutOf{err.resource}"
        try:
            self.clientset.pods.update_status(update)
        except (NotFoundError, ConflictError):
            pass

    def _set_disk_pressure_condition(self, pressure: bool) -> None:
        if pressure == getattr(self, "_last_disk_pressure", False):
            return
        want = "True" if pressure else "False"

        def _mutate(cur: api.Node) -> api.Node:
            c = cur.status.condition(api.NODE_DISK_PRESSURE)
            if c is None:
                if not pressure:
                    return cur
                c = api.NodeCondition(type=api.NODE_DISK_PRESSURE)
                cur.status.conditions.append(c)
            c.status = want
            return cur

        try:
            self.clientset.nodes.guaranteed_update(self.node_name, _mutate, "")
            self._last_disk_pressure = pressure
        except NotFoundError:
            pass

    def _set_pressure_condition(self, pressure: bool) -> None:
        # this kubelet exclusively owns its node's pressure condition, so
        # the last pushed value is authoritative — no read needed
        if pressure == getattr(self, "_last_pressure", False):
            return
        want = "True" if pressure else "False"

        def _mutate(cur: api.Node) -> api.Node:
            c = cur.status.condition(api.NODE_MEMORY_PRESSURE)
            if c is None:
                if not pressure:
                    return cur
                c = api.NodeCondition(type=api.NODE_MEMORY_PRESSURE)
                cur.status.conditions.append(c)
            c.status = want
            return cur

        try:
            self.clientset.nodes.guaranteed_update(self.node_name, _mutate, "")
            self._last_pressure = pressure
        except NotFoundError:
            pass

    def _set_running(self, pod: api.Pod, now: float) -> bool:
        # pod may be a shared informer-cache object (PodNodeIndex path):
        # never mutate it — build the status update on a private copy
        update = api.Pod.from_dict(pod.to_dict())
        update.status.phase = api.RUNNING
        update.status.host_ip = self.node_name
        if not update.status.pod_ip:
            # the CNI ADD step of pod startup (pkg/kubelet/network): the
            # plugin leases an address the moment the sandbox runs;
            # failure keeps the pod Pending, like a failed CNI ADD
            if pod.spec.host_network:
                update.status.pod_ip = self.node_name
            else:
                from .network import NetworkSetupError

                try:
                    update.status.pod_ip = self._network().setup_pod(pod.meta.key)
                except NetworkSetupError:
                    return False
        try:
            self.clientset.pods.update_status(update)
            return True
        except (NotFoundError, ConflictError):
            if not pod.spec.host_network and self.network is not None:
                self.network.teardown_pod(pod.meta.key)  # lease back
            return False

    def _network(self):
        """The network plugin, built on first use so the node's ALLOCATED
        podCIDR (IPAM controller) wins over the hash fallback.  While the
        plugin is still on the fallback base AND has leased nothing, each
        call re-checks the node — a CIDR that lands after the first probe
        (IPAM races pod starts) still takes effect before any address
        goes out under the hash base."""
        from .network import KubenetPlugin

        needs_probe = (self.network is None
                       or (not self.network.has_cidr
                           and not self.network.leased()))
        if needs_probe:
            cidr = ""
            try:
                cidr = self.clientset.nodes.get(self.node_name).spec.pod_cidr
            except Exception as e:  # noqa: BLE001 - fall through to the hash base
                logger.debug("%s: podCIDR probe failed (%s); using hash "
                             "fallback base", self.node_name,
                             type(e).__name__)
            if self.network is None or (cidr and "/" in cidr):
                self.network = KubenetPlugin(self.node_name, cidr)
        return self.network

    def _heartbeat(self, force: bool = False) -> None:
        now = self._clock()
        if not force and now - self._last_heartbeat < self.heartbeat_interval:
            return
        self._last_heartbeat = now

        def _mutate(cur: api.Node) -> api.Node:
            c = cur.status.condition(api.NODE_READY)
            if c is None:
                c = api.NodeCondition(type=api.NODE_READY)
                cur.status.conditions.append(c)
            c.status = "True"
            c.heartbeat_time = now
            c.heartbeat_revision = cur.meta.resource_version
            # a restarted kubelet binds a fresh port: the endpoint must
            # follow the heartbeat, not only initial registration
            if self.server is not None:
                cur.status.kubelet_url = self.server.url
            # and volumesInUse is always THIS process's truth — a restart
            # clears stale mounts so the AD controller can detach
            cur.status.volumes_in_use = self.volume_manager.volumes_in_use()
            return cur

        try:
            self.clientset.nodes.guaranteed_update(self.node_name, _mutate, "")
        except NotFoundError:
            self.register()


class HollowFleet:
    """N hollow kubelets against one control plane (start-kubemark.sh),
    sharing one pod informer + by-node index."""

    def __init__(
        self,
        clientset: Clientset,
        n: int,
        clock: Callable[[], float] = time.monotonic,
        **kubelet_kw,
    ):
        self.informer = SharedInformer(clientset.pods)
        self.index = PodNodeIndex(self.informer)
        self.kubelets = [
            HollowKubelet(
                clientset, f"hollow-{i:05d}", pod_index=self.index, clock=clock, **kubelet_kw
            )
            for i in range(n)
        ]

    def register_all(self) -> None:
        for k in self.kubelets:
            k.register()
        self.informer.start_manual()

    def tick_all(self) -> dict:
        self.informer.pump()
        total = {"started": 0, "observed": 0, "restarts": 0, "evicted": 0}
        for k in self.kubelets:
            r = k.tick()
            for key in total:
                total[key] += r[key]
        return total


class HollowWatcher:
    """Kubemark-shaped hollow WATCHER (the serving-tier analogue of
    :class:`HollowKubelet`): a real watch stream feeding a minimal
    informer cache (key → resourceVersion) with no controller
    underneath.  Thread-cheap by construction — no thread, no typed
    decode, no handler fan-out; the fleet driver pumps it cooperatively
    — so 10k+ of them fit in one process, which is how many-client
    fan-out behavior is tested on one machine (the kubemark trick,
    applied to watch traffic instead of nodes).

    Works over any watch with ``get(timeout)``/``stop()`` and the
    event/frame duck types: the in-process ``Store.watch`` queue or a
    ``RemoteWatch`` HTTP stream.  Applies the same revision fence as
    ``SharedInformer`` (stale deliveries skipped), so its final cache is
    exactly the state-equivalence surface the fleet bench gates on."""

    __slots__ = ("id", "watch", "cache", "applied_rev", "deliveries",
                 "event_units", "gaps", "tracker")

    def __init__(self, client_id: str, watch, tracker=None):
        from ..utils.fanout import WatchFanoutTracker  # noqa: F401 (typing aid)

        self.id = client_id
        self.watch = watch
        # bounded: one int per live object key (the hollow informer cache)
        self.cache: dict = {}
        self.applied_rev = 0
        self.deliveries = 0   # queue items consumed (a frame counts 1)
        self.event_units = 0  # events represented (a frame counts len())
        self.gaps = 0
        self.tracker = tracker
        if tracker is not None:
            tracker.register(client_id)

    def pump(self, budget: Optional[int] = None) -> int:
        """Drain up to ``budget`` queued deliveries (None = everything
        waiting) and report the applied revision to the tracker once per
        pump, not per item — the fan-out hot path stays two dict ops."""
        from ..store.frames import FRAME
        from ..store.store import DELETED, WATCH_GAP

        n = 0
        while budget is None or n < budget:
            item = self.watch.get(timeout=0)
            if item is None:
                break
            t = item.type
            if t == FRAME:
                fence = self.applied_rev
                for i in range(len(item.keys)):
                    rev = item.revisions[i]
                    if rev <= fence:
                        continue  # straggler inside a superseded frame
                    if item.types[i] == DELETED:
                        self.cache.pop(item.keys[i], None)
                    else:
                        self.cache[item.keys[i]] = rev
                if item.revision > self.applied_rev:
                    self.applied_rev = item.revision
                self.event_units += len(item.keys)
            elif t == WATCH_GAP:
                # continuity lost (410 analogue): a hollow watcher has no
                # lister to rebuild from — count it; the fleet bench
                # treats any gapped client as dropped-state
                self.gaps += 1
            else:
                if item.revision <= self.applied_rev:
                    n += 1
                    continue  # revision fence, as SharedInformer applies it
                if t == DELETED:
                    self.cache.pop(item.key, None)
                else:
                    self.cache[item.key] = item.revision
                self.applied_rev = item.revision
                self.event_units += 1
            self.deliveries += 1
            n += 1
        if n and self.tracker is not None:
            self.tracker.report(self.id, self.applied_rev)
        return n

    def stop(self) -> None:
        self.watch.stop()
        if self.tracker is not None:
            self.tracker.unregister(self.id)


class HollowWatcherFleet:
    """N hollow watchers on one watch source — the many-client axis of
    the serving-tier bench.  ``source`` is anything with
    ``watch(kind, frames=...)`` (a ``Store`` or a ``RemoteStore``); the
    caller drives ``pump_all`` from however many threads it wants (the
    watchers are partitionable by slice — no shared mutable state
    between them beyond the tracker's locked dict)."""

    def __init__(self, source, n: int, kind: str = "Pod",
                 frames: bool = True, tracker=None, prefix: str = "hw",
                 from_revision: Optional[int] = None):
        self.tracker = tracker
        self.watchers = [
            HollowWatcher(
                f"{prefix}-{i:05d}",
                source.watch(kind, from_revision=from_revision,
                             frames=frames),
                tracker,
            )
            for i in range(n)
        ]

    def pump_all(self, budget: Optional[int] = None) -> int:
        return sum(w.pump(budget) for w in self.watchers)

    def converged(self, head: int) -> int:
        """How many watchers have applied everything up to ``head``."""
        return sum(1 for w in self.watchers if w.applied_rev >= head)

    def stop_all(self) -> None:
        for w in self.watchers:
            w.stop()
