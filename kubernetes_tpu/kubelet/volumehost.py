"""On-disk pod volumes: emptyDir / hostPath / configMap / secret /
downwardAPI materialized in the filesystem.

Capability of the reference's no-cloud volume plugins
(``pkg/volume/empty_dir/empty_dir.go``, ``host_path/``, ``configmap/``,
``secret/``, ``downwardapi/``) and the piece of the mount reconciler
(``pkg/kubelet/volumemanager/reconciler/reconciler.go:165``) they need:
every sync pass makes the on-disk state match the API state.

ConfigMap/secret/downwardAPI volumes use the reference's **atomic
writer** layout (``pkg/volume/util/atomic_writer.go``): payload files
live in a timestamped ``..<ts>`` directory, a ``..data`` symlink points
at the current one, and user-visible keys are symlinks through
``..data/<key>`` — so an update swaps ONE symlink and a reader never
observes a half-written payload.  A container holding the volume open
sees the new content on the next open, exactly like a real projected
volume update.

Container view: each volume mount becomes a symlink at
``<rootfs>/<mountPath>`` pointing into the pod's volume dir, so exec'd
commands resolve ``<mountPath>/key`` naturally (rootfs-relative absolute
paths — the unprivileged stand-in for a bind mount).
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import threading
from typing import Callable, Optional

from ..api import types as api

log = logging.getLogger("kubernetes_tpu.kubelet")


def _valid_payload_key(k: str) -> bool:
    """``atomic_writer.go validatePayload``: a key names ONE file in the
    volume dir — reject empty, ``.``/``..``, anything ``..``-prefixed
    (collides with the atomic writer's internal ``..data``/``..<ts>``
    namespace), and any path separator (this flat layout projects each
    key as a single symlink, so traversal and nesting are both out)."""
    return bool(k) and k not in (".", "..") and not k.startswith("..") \
        and "/" not in k and os.sep not in k and not os.path.isabs(k)


class VolumeHost:
    """Materializes local volumes under ``<root>/<pod>/volumes/<name>``."""

    def __init__(self, root: Optional[str] = None,
                 fetch_configmap: Optional[Callable[[str, str], Optional[dict]]] = None,
                 fetch_secret: Optional[Callable[[str, str], Optional[dict]]] = None):
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="ktpu-volumes-")
        # name resolvers: (namespace, name) -> data dict | None
        self.fetch_configmap = fetch_configmap or (lambda ns, n: None)
        self.fetch_secret = fetch_secret or (lambda ns, n: None)
        self._mu = threading.Lock()
        self._ts = 0  # monotonic payload-dir counter (the ..<ts> names)
        self._warned_keys: dict[str, frozenset] = {}  # vol_dir -> last bad set
        self.stats = {"mounts": 0, "updates": 0, "unmounts": 0}

    def pod_volumes_dir(self, pod_key: str) -> str:
        return os.path.join(self.root, pod_key.replace("/", "_"), "volumes")

    def volume_path(self, pod_key: str, volume_name: str) -> str:
        return os.path.join(self.pod_volumes_dir(pod_key), volume_name)

    @staticmethod
    def is_local(vol: api.Volume) -> bool:
        return bool(vol.empty_dir or vol.host_path or vol.config_map_name
                    or vol.secret_name or vol.downward_api)

    # -- the reconciler pass -------------------------------------------------
    def sync_pod(self, pod: api.Pod) -> int:
        """Make every local volume of ``pod`` present and current on
        disk; returns how many payloads were (re)written.  Idempotent:
        unchanged payloads are left untouched (symlink flip only when
        content differs)."""
        changed = 0
        for vol in pod.spec.volumes:
            if not self.is_local(vol):
                continue
            path = self.volume_path(pod.meta.key, vol.name)
            if vol.empty_dir:
                if not os.path.isdir(path):
                    os.makedirs(path, exist_ok=True)
                    self.stats["mounts"] += 1
                continue
            if vol.host_path:
                # hostPath: a symlink to the host location (the bind-mount
                # analogue); dangling allowed like type: "" in the reference
                if not os.path.islink(path):
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    os.symlink(vol.host_path, path)
                    self.stats["mounts"] += 1
                continue
            payload = self._payload_for(pod, vol)
            if payload is None:
                continue  # source object missing: keep the last payload
            if self._atomic_write(path, payload):
                changed += 1
        return changed

    def _payload_for(self, pod: api.Pod, vol: api.Volume) -> Optional[dict[str, bytes]]:
        ns = pod.meta.namespace
        if vol.config_map_name:
            data = self.fetch_configmap(ns, vol.config_map_name)
            if data is None:
                return None
            return {k: str(v).encode() for k, v in data.items()}
        if vol.secret_name:
            data = self.fetch_secret(ns, vol.secret_name)
            if data is None:
                return None
            out = {}
            for k, v in data.items():
                out[k] = v if isinstance(v, bytes) else str(v).encode()
            return out
        if vol.downward_api:
            out = {}
            for fname, ref in vol.downward_api.items():
                out[fname] = self._downward_value(pod, ref).encode()
            return out
        return None

    @staticmethod
    def _downward_value(pod: api.Pod, ref: str) -> str:
        """``metadata.name`` / ``metadata.namespace`` /
        ``metadata.labels['k']`` / ``metadata.annotations['k']``
        (the downward API fieldRef subset)."""
        if ref == "metadata.name":
            return pod.meta.name
        if ref == "metadata.namespace":
            return pod.meta.namespace
        for prefix, src in (("metadata.labels['", pod.meta.labels),
                            ("metadata.annotations['", pod.meta.annotations)):
            if ref.startswith(prefix) and ref.endswith("']"):
                return str(src.get(ref[len(prefix):-2], ""))
        return ""

    def _atomic_write(self, vol_dir: str, payload: dict[str, bytes]) -> bool:
        """atomic_writer.go: write ``..<ts>``, flip ``..data``, project
        keys as symlinks.  Returns True when content actually changed."""
        bad = frozenset(k for k in payload if not _valid_payload_key(k))
        if bad:
            if self._warned_keys.get(vol_dir) != bad:  # once per key set,
                self._warned_keys[vol_dir] = bad       # not per sync tick
                log.warning("volume %s: skipping invalid payload key(s) %s",
                            vol_dir, sorted(bad))
            payload = {k: v for k, v in payload.items() if k not in bad}
        else:
            # a payload gone clean re-arms the warning for this dir
            self._warned_keys.pop(vol_dir, None)
        with self._mu:
            os.makedirs(vol_dir, exist_ok=True)
            data_link = os.path.join(vol_dir, "..data")
            current = None
            if os.path.islink(data_link):
                current = {}
                cur_dir = os.path.join(vol_dir, os.readlink(data_link))
                try:
                    for k in os.listdir(cur_dir):
                        with open(os.path.join(cur_dir, k), "rb") as f:
                            current[k] = f.read()
                except OSError:
                    current = None
            if current == payload:
                return False
            self._ts += 1
            ts_name = f"..{self._ts:010d}"
            ts_dir = os.path.join(vol_dir, ts_name)
            os.makedirs(ts_dir, exist_ok=True)
            for k, v in payload.items():
                with open(os.path.join(ts_dir, k), "wb") as f:
                    f.write(v)
            # flip: symlink swap via rename is the atomic step
            tmp_link = os.path.join(vol_dir, "..data_tmp")
            if os.path.islink(tmp_link):
                os.unlink(tmp_link)
            os.symlink(ts_name, tmp_link)
            old_target = os.readlink(data_link) if os.path.islink(data_link) else None
            os.replace(tmp_link, data_link)
            # project keys through ..data (stable across updates)
            for k in payload:
                key_link = os.path.join(vol_dir, k)
                if not os.path.islink(key_link):
                    os.symlink(os.path.join("..data", k), key_link)
            for k in list(os.listdir(vol_dir)):
                if k.startswith(".."):
                    continue
                if k not in payload:
                    os.unlink(os.path.join(vol_dir, k))
            if old_target is not None and old_target != ts_name:
                shutil.rmtree(os.path.join(vol_dir, old_target),
                              ignore_errors=True)
                self.stats["updates"] += 1
            else:
                self.stats["mounts"] += 1
            return True

    # -- container projection ------------------------------------------------
    def project_into_rootfs(self, pod: api.Pod, container: api.Container,
                            rootfs: str) -> None:
        """Symlink each volumeMount at ``<rootfs>/<mountPath>`` (the
        unprivileged bind-mount: commands exec'd with cwd=rootfs resolve
        ``mountPath/key`` through the live volume dir)."""
        by_name = {v.name: v for v in pod.spec.volumes}
        for m in container.volume_mounts:
            vol = by_name.get(m.name)
            if vol is None or not self.is_local(vol):
                continue
            target = self.volume_path(pod.meta.key, m.name)
            link = os.path.normpath(
                os.path.join(rootfs, m.mount_path.lstrip("/")))
            # separator-anchored escape guard: mountPath is API-controlled
            # spec data and a ".."-bearing path must never reach the host
            # (same contract as hollow._rootfs_path for kubectl cp)
            if link == rootfs or not link.startswith(rootfs + os.sep):
                continue
            os.makedirs(os.path.dirname(link), exist_ok=True)
            if os.path.islink(link):
                if os.readlink(link) == target:
                    continue
                os.unlink(link)
            elif os.path.isdir(link):
                shutil.rmtree(link, ignore_errors=True)
            os.symlink(target, link)

    def teardown_pod(self, pod_key: str) -> None:
        pod_dir = os.path.dirname(self.pod_volumes_dir(pod_key))
        if os.path.isdir(pod_dir):
            shutil.rmtree(pod_dir, ignore_errors=True)
            self.stats["unmounts"] += 1
        for d in [d for d in self._warned_keys
                  if d.startswith(pod_dir + os.sep)]:
            self._warned_keys.pop(d)

    def teardown_all(self) -> None:
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)
        self._warned_keys.clear()
