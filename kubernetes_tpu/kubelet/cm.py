"""Node resource management: the cgroup hierarchy + image GC analogues.

Two reference kubelet subsystems the hollow node previously lacked
(VERDICT r2 ask #6):

- **ContainerManager** (``pkg/kubelet/cm/container_manager_linux.go``,
  ``qos_container_manager_linux.go``): a node-allocatable cgroup tree —
  root → kubepods → {guaranteed at top level, burstable, besteffort} →
  pod — with the reference's accounting rules: allocatable = capacity −
  system-reserved − kube-reserved; per-pod cpu shares =
  max(2, milliCPU × 1024 / 1000) (``helpers_linux.go MilliCPUToShares``);
  Guaranteed pods parent directly under kubepods, Burstable/BestEffort
  under their QoS cgroup whose cpu shares are the live sum of member
  requests (``qos_container_manager_linux.go setCPUCgroupConfig``).
  Admission debits requests against allocatable — a pod that does not
  fit is REJECTED at the node (the kubelet's OutOf<resource> path),
  independent of what the scheduler thought.  Observed usage is charged
  into the pod cgroup and rolls up the tree, so memory pressure is an
  ACCOUNTED signal (root usage vs threshold), not a scripted one.

- **ImageManager** (``pkg/kubelet/images/image_gc_manager.go``): images
  pull on first reference with deterministic pseudo-sizes, are
  ref-counted by running pods, age while unreferenced, and are LRU
  garbage-collected when disk usage crosses ``high_threshold`` down to
  ``low_threshold``; failure to reach it raises the disk-pressure signal
  the eviction manager consumes.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api import types as api
from .runtime import QOS_BEST_EFFORT, QOS_BURSTABLE, QOS_GUARANTEED, pod_qos_class


def milli_cpu_to_shares(milli: int) -> int:
    """helpers_linux.go MilliCPUToShares (min 2, the kernel floor)."""
    return max(2, milli * 1024 // 1000)


def _pod_requests(pod: api.Pod) -> tuple[int, int]:
    """(milliCPU, memory bytes) summed over containers."""
    cpu = mem = 0
    for c in pod.spec.containers:
        r = c.resources.requests
        q = r.get("cpu")
        if q is not None:
            cpu += int(api.Quantity(str(q)).milli_value())
        q = r.get("memory")
        if q is not None:
            mem += int(api.Quantity(str(q)).value())
    return cpu, mem


@dataclass
class Cgroup:
    """One node in the hierarchy: configured shares/limits + live charges."""

    name: str
    cpu_shares: int = 2
    memory_limit: Optional[int] = None  # None = unlimited
    memory_usage: int = 0  # charged (observed) bytes, rolled up by parent
    children: dict[str, "Cgroup"] = field(default_factory=dict)

    def usage(self) -> int:
        return self.memory_usage + sum(c.usage() for c in self.children.values())


class AdmissionRejected(Exception):
    """The node cannot host the pod (OutOfcpu / OutOfmemory / OutOfpods)."""

    def __init__(self, resource: str, message: str):
        self.resource = resource
        super().__init__(message)


class ContainerManager:
    """The node's resource ledger + cgroup tree."""

    def __init__(self, cpu: str, memory: str, max_pods: int,
                 system_reserved_cpu: str = "0",
                 system_reserved_memory: str = "0",
                 kube_reserved_cpu: str = "0",
                 kube_reserved_memory: str = "0"):
        self.capacity_cpu = int(api.Quantity(cpu).milli_value())
        self.capacity_memory = int(api.Quantity(memory).value())
        self.max_pods = max_pods
        reserved_cpu = (int(api.Quantity(system_reserved_cpu).milli_value())
                        + int(api.Quantity(kube_reserved_cpu).milli_value()))
        reserved_mem = (int(api.Quantity(system_reserved_memory).value())
                        + int(api.Quantity(kube_reserved_memory).value()))
        # NodeAllocatable (container_manager_linux.go GetNodeAllocatable)
        self.allocatable_cpu = max(0, self.capacity_cpu - reserved_cpu)
        self.allocatable_memory = max(0, self.capacity_memory - reserved_mem)
        # the tree: kubepods → {burstable, besteffort} (+ guaranteed pods
        # directly under kubepods, like the reference layout)
        self.root = Cgroup("kubepods",
                           cpu_shares=milli_cpu_to_shares(self.allocatable_cpu),
                           memory_limit=self.allocatable_memory)
        self.root.children["burstable"] = Cgroup("kubepods/burstable")
        self.root.children["besteffort"] = Cgroup("kubepods/besteffort",
                                                  cpu_shares=2)
        # pod ledger: key -> (qos, milliCPU, memory)
        self._pods: dict[str, tuple[str, int, int]] = {}
        self.reserved_cpu = 0
        self.reserved_memory = 0

    # -- admission (kubelet canAdmitPod over allocatable) -------------------
    def admit(self, pod: api.Pod) -> None:
        """Raises AdmissionRejected when requests exceed what's left of
        node allocatable — the node-side backstop behind the scheduler."""
        if pod.meta.key in self._pods:
            return
        cpu, mem = _pod_requests(pod)
        if len(self._pods) + 1 > self.max_pods:
            raise AdmissionRejected("pods", f"node holds {len(self._pods)} pods, max {self.max_pods}")
        if self.reserved_cpu + cpu > self.allocatable_cpu:
            raise AdmissionRejected(
                "cpu", f"requested {cpu}m, {self.allocatable_cpu - self.reserved_cpu}m allocatable left")
        if self.reserved_memory + mem > self.allocatable_memory:
            raise AdmissionRejected(
                "memory", f"requested {mem}B, {self.allocatable_memory - self.reserved_memory}B allocatable left")

    def add_pod(self, pod: api.Pod, force: bool = False) -> Cgroup:
        """Create the pod cgroup in its QoS parent and debit the ledger.
        ``force`` skips admission — for pods observed ALREADY running
        (kubelet restart recovery), which are never re-admitted."""
        key = pod.meta.key
        if key in self._pods:
            return self._find_pod_cgroup(key)
        if not force:
            self.admit(pod)
        qos = pod_qos_class(pod)
        cpu, mem = _pod_requests(pod)
        cg = Cgroup(f"pod{pod.meta.uid or key}",
                    cpu_shares=milli_cpu_to_shares(cpu),
                    # Guaranteed pods are limited to their (== request)
                    # bound; others inherit the parent bound
                    memory_limit=mem if qos == QOS_GUARANTEED and mem else None)
        parent = self._qos_parent(qos)
        parent.children[key] = cg
        self._pods[key] = (qos, cpu, mem)
        self.reserved_cpu += cpu
        self.reserved_memory += mem
        self._recompute_qos_shares()
        return cg

    def remove_pod(self, pod_key: str) -> None:
        rec = self._pods.pop(pod_key, None)
        if rec is None:
            return
        qos, cpu, mem = rec
        self._qos_parent(qos).children.pop(pod_key, None)
        self.reserved_cpu -= cpu
        self.reserved_memory -= mem
        self._recompute_qos_shares()

    def known(self) -> set[str]:
        return set(self._pods)

    def _qos_parent(self, qos: str) -> Cgroup:
        if qos == QOS_GUARANTEED:
            return self.root
        return self.root.children[
            "burstable" if qos == QOS_BURSTABLE else "besteffort"]

    def _find_pod_cgroup(self, key: str) -> Optional[Cgroup]:
        qos, _, _ = self._pods[key]
        return self._qos_parent(qos).children.get(key)

    def _recompute_qos_shares(self) -> None:
        """setCPUCgroupConfig: burstable shares track the live sum of
        member requests; besteffort stays at the kernel floor."""
        total = sum(cpu for qos, cpu, _ in self._pods.values()
                    if qos == QOS_BURSTABLE)
        self.root.children["burstable"].cpu_shares = milli_cpu_to_shares(total)

    # -- usage accounting (the cadvisor feed) ------------------------------
    def charge_usage(self, usage_by_pod: dict[str, int]) -> None:
        """Write observed per-pod memory into each pod cgroup (absolute,
        not incremental — mirrors a stats sample)."""
        for key in self._pods:
            cg = self._find_pod_cgroup(key)
            if cg is not None:
                cg.memory_usage = usage_by_pod.get(key, 0)

    def node_usage(self) -> int:
        """Accounted memory use: the root rollup."""
        return self.root.usage()

    def qos_usage(self, qos: str) -> int:
        if qos == QOS_GUARANTEED:
            return sum(c.usage() for k, c in self.root.children.items()
                       if k not in ("burstable", "besteffort"))
        return self._qos_parent(qos).usage()


# -- image GC ----------------------------------------------------------------

@dataclass
class _Image:
    name: str
    size: int
    refs: int = 0
    last_used: float = 0.0
    first_detected: float = 0.0


class ImageManager:
    """Pull bookkeeping + LRU garbage collection over a disk budget."""

    def __init__(self, disk_capacity: int = 100 << 30,
                 high_threshold: float = 0.85, low_threshold: float = 0.80,
                 min_age: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self.disk_capacity = disk_capacity
        self.high_threshold = high_threshold
        self.low_threshold = low_threshold
        self.min_age = min_age
        self.clock = clock
        self._images: dict[str, _Image] = {}
        # pod key -> image names it references
        self._pod_images: dict[str, set[str]] = {}
        self.stats = {"pulled": 0, "removed": 0, "reclaimed_bytes": 0}

    @staticmethod
    def image_size(name: str) -> int:
        """Deterministic pseudo-size (64–576 MiB) — the fake-runtime
        stand-in for a registry manifest size."""
        return (64 + (zlib.crc32(name.encode()) % 512)) << 20

    def disk_used(self) -> int:
        return sum(im.size for im in self._images.values())

    def ensure_pulled(self, pod: api.Pod) -> list[str]:
        """Pull every container image the pod references (no-op when
        present) and take refs.  Returns newly pulled names."""
        now = self.clock()
        key = pod.meta.key
        wanted = {c.image or f"img-{c.name}" for c in pod.spec.containers}
        pulled = []
        for name in wanted:
            im = self._images.get(name)
            if im is None:
                im = self._images[name] = _Image(
                    name=name, size=self.image_size(name),
                    first_detected=now)
                self.stats["pulled"] += 1
                pulled.append(name)
            im.last_used = now
        prev = self._pod_images.get(key, set())
        for name in wanted - prev:
            self._images[name].refs += 1
        self._pod_images[key] = wanted
        return pulled

    def release(self, pod_key: str) -> None:
        now = self.clock()
        for name in self._pod_images.pop(pod_key, set()):
            im = self._images.get(name)
            if im is not None:
                im.refs = max(0, im.refs - 1)
                im.last_used = now

    def garbage_collect(self) -> dict:
        """image_gc_manager.go GarbageCollect: over ``high_threshold`` →
        free LRU unreferenced images (older than min_age) until under
        ``low_threshold``.  Returns {freed, used, over} — ``over`` True
        means even a full sweep could not reach the target (the caller
        raises disk pressure)."""
        used = self.disk_used()
        high = int(self.disk_capacity * self.high_threshold)
        if used <= high:
            return {"freed": 0, "used": used, "over": False}
        target = int(self.disk_capacity * self.low_threshold)
        now = self.clock()
        candidates = sorted(
            (im for im in self._images.values()
             if im.refs == 0 and now - im.first_detected >= self.min_age),
            key=lambda im: im.last_used)
        freed = 0
        for im in candidates:
            if used - freed <= target:
                break
            del self._images[im.name]
            freed += im.size
            self.stats["removed"] += 1
        self.stats["reclaimed_bytes"] += freed
        used -= freed
        return {"freed": freed, "used": used, "over": used > target}

    def images(self) -> list[str]:
        return sorted(self._images)
