"""Hollow-node daemon (reference ``cmd/kubemark/hollow-node.go``): a
hollow kubelet plus (optionally) a hollow proxy against a remote
apiserver.

    python -m kubernetes_tpu.kubelet --apiserver http://host:6443 \
        --name node-001 [--count 50] [--proxy] [--tick 1.0]

``--count N`` runs a fleet of N nodes named ``{name}-{i:05d}`` in one
process (kubemark's N-hollow-nodes-per-host packing)."""

from __future__ import annotations

import argparse
import logging
import sys

from ..daemon import install_signal_stop, remote_clientset, wait_forever
from .hollow import HollowFleet, HollowKubelet


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.kubelet")
    ap.add_argument("--apiserver", default=None)
    ap.add_argument("--token", default=None)
    ap.add_argument("--kubeconfig", default=None,
                    help="connection document from the kubeadm kubeconfig "
                    "phase (server + CA pin + client cert); --apiserver/"
                    "--token override its fields")
    ap.add_argument("--name", default="hollow")
    ap.add_argument("--count", type=int, default=1)
    ap.add_argument("--proxy", action="store_true")
    ap.add_argument("--tick", type=float, default=1.0)
    ap.add_argument("--cpu", default="8")
    ap.add_argument("--memory", default="16Gi")
    ap.add_argument("--serve-logs", action="store_true",
                    help="expose the kubelet read API (logs/pods/healthz)")
    ap.add_argument("--real-containers", action="store_true",
                    help="run containers as real child processes with "
                    "on-disk volumes (single-node depth; not for fleets)")
    ap.add_argument("--container-root", default=None,
                    help="persistent container/checkpoint root: a "
                    "restarted kubelet adopts still-live containers "
                    "(dockershim checkpoint recovery)")
    ap.add_argument("--static-pod-dir", default=None,
                    help="directory of pod manifests to run WITHOUT a "
                    "scheduler, mirrored into the API (kubeadm-style "
                    "static pods)")
    ap.add_argument("--feature-gates", default="",
                    help="A=true,B=false (e.g. DynamicKubeletConfig=true)")
    ap.add_argument("--healthz-port", type=int, default=-1,
                    help="serve /healthz + /metrics + /debug/* (reference "
                         ":10248); -1 = off, 0 = ephemeral")
    ap.add_argument("--timeseries", action="store_true",
                    help="scrape the client-metrics registry into "
                         "time-series rings (served at /debug/timeseries)")
    ap.add_argument("--timeseries-interval", type=float, default=1.0)
    ap.add_argument("--telemetry-sink", default=None,
                    help="ship flight dumps + time-series deltas off-box "
                         "(collector URL or JSON-lines file path)")
    args = ap.parse_args(argv)
    if args.feature_gates:
        from ..utils.features import DEFAULT_FEATURE_GATES

        DEFAULT_FEATURE_GATES.set_from_string(args.feature_gates)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if not args.apiserver and not args.kubeconfig:
        ap.error("one of --apiserver or --kubeconfig is required")
    cs = remote_clientset(args.apiserver, args.token,
                          kubeconfig=args.kubeconfig)
    if args.count > 1 and (args.static_pod_dir or args.real_containers
                           or args.container_root):
        logging.warning("--static-pod-dir/--real-containers/--container-root "
                        "are single-node options; a --count fleet ignores them")
    if args.count > 1:
        fleet = HollowFleet(cs, args.count, cpu=args.cpu, memory=args.memory,
                            serve=args.serve_logs)
        # kubemark names nodes per host; keep the given prefix
        for i, k in enumerate(fleet.kubelets):
            k.node_name = f"{args.name}-{i:05d}"
        fleet.register_all()
        kubelets = fleet.kubelets
        tick = fleet.tick_all
    else:
        k = HollowKubelet(cs, args.name, cpu=args.cpu, memory=args.memory,
                          serve=args.serve_logs,
                          real_containers=args.real_containers,
                          container_root=args.container_root,
                          static_pod_dir=args.static_pod_dir)
        kubelets = [k]
        if args.static_pod_dir:
            # kubeadm bootstrap: the control-plane kubelet comes up BEFORE
            # its own static-pod apiserver — run manifests standalone and
            # keep retrying registration until the API answers
            state = {"registered": False}
            base_tick = k.tick

            def tick() -> None:
                if not state["registered"]:
                    k.standalone_static_tick()
                    try:
                        k.register()
                        state["registered"] = True
                        logging.info("apiserver reachable: node registered; "
                                     "static pods will be mirrored")
                    except Exception as e:  # noqa: BLE001 — stay standalone
                        # log on CHANGE so "API still coming up" is quiet
                        # but a persistent credential failure (401, bad
                        # CA) is diagnosable
                        msg = f"{type(e).__name__}: {e}"
                        if msg != state.get("last_err"):
                            state["last_err"] = msg
                            logging.warning(
                                "registration failed (still standalone, "
                                "will retry): %s", msg)
                        return
                base_tick()
        else:
            k.register()
            tick = k.tick

    proxies = []
    if args.proxy:
        from ..proxy import HollowProxyFleet

        pf = HollowProxyFleet(cs, [k.node_name for k in kubelets])
        pf.start()
        proxies.append(pf)

    # the shared daemon health surface (the reference kubelet's :10248):
    # hollow nodes observe through the client transport registry
    from ..daemon import serve_health
    from ..utils.metrics import DEFAULT_CLIENT_METRICS

    health = serve_health(args.healthz_port,
                          DEFAULT_CLIENT_METRICS.registry)
    if health is not None:
        logging.info("healthz/metrics on :%d", health.local_port)
    if args.timeseries or args.telemetry_sink:
        from ..daemon import enable_continuous_telemetry

        enable_continuous_telemetry(
            DEFAULT_CLIENT_METRICS.registry,
            interval_s=args.timeseries_interval,
            sink_spec=args.telemetry_sink)

    logging.info("hollow node(s) running: %d kubelet(s), proxy=%s",
                 len(kubelets), bool(proxies))

    def one_tick() -> None:
        # node loops never die: a transient apiserver error must not take
        # down the whole N-node fleet process
        try:
            tick()
            for pf in proxies:
                pf.tick_all()
        except Exception:
            logging.exception("hollow tick failed (will retry)")

    stop = install_signal_stop()
    try:
        wait_forever(stop, tick=one_tick, interval=args.tick)
    finally:
        if health is not None:
            health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
