"""The kubelet's network plugin seam — pod address lifecycle.

Capability of ``pkg/kubelet/network`` (the CNI/kubenet plugin manager):
pod sandboxes get their address through a pluggable interface with a real
setup/teardown lifecycle, not an ambient counter.  The kubenet-analogue
plugin runs a real IPAM over the node's allocated podCIDR: addresses are
leased per pod, released on teardown, reused after release, and
exhaustion is a hard error the kubelet surfaces (the reference's CNI
ADD failure keeps the pod from starting).

Host-network pods bypass the plugin entirely and take the node's own
address, exactly like ``hostNetwork: true``.
"""

from __future__ import annotations

import zlib
from typing import Optional


class NetworkSetupError(Exception):
    """CNI ADD failed (exhausted range, plugin misconfigured)."""


class NetworkPlugin:
    """The seam (reference ``network.NetworkPlugin``)."""

    name = "noop"

    def setup_pod(self, pod_key: str) -> str:
        raise NotImplementedError

    def teardown_pod(self, pod_key: str) -> None:
        raise NotImplementedError

    def pod_ip(self, pod_key: str) -> Optional[str]:
        raise NotImplementedError

    def status(self) -> dict:
        return {"name": self.name}


class KubenetPlugin(NetworkPlugin):
    """Kubenet-shaped IPAM over the node's podCIDR.

    One /24-style range per node: .1 is reserved for the bridge (cbr0),
    pods lease .2–.254, leases release on teardown and recycle
    lowest-free-first (the host-local IPAM allocator's behavior)."""

    name = "kubenet"

    def __init__(self, node_name: str, pod_cidr: str = ""):
        self.node_name = node_name
        self.has_cidr = bool(pod_cidr and "/" in pod_cidr)
        if self.has_cidr:
            self.base = pod_cidr.split("/", 1)[0].rsplit(".", 1)[0]
        else:
            # no CIDR allocated (IPAM controller absent): a stable
            # crc32-derived base — never hash(), which is seed-randomized
            h = zlib.crc32(node_name.encode()) & 0xFFFF
            self.base = f"10.{h >> 8}.{h & 0xFF}"
        self._leases: dict[str, int] = {}  # pod key -> host octet
        self._in_use: set[int] = {1}  # .1 = the bridge
        self.stats = {"setups": 0, "teardowns": 0, "exhausted": 0}

    def setup_pod(self, pod_key: str) -> str:
        n = self._leases.get(pod_key)
        if n is None:
            for cand in range(2, 255):  # lowest-free-first (host-local)
                if cand not in self._in_use:
                    n = cand
                    break
            else:
                self.stats["exhausted"] += 1
                raise NetworkSetupError(
                    f"podCIDR {self.base}.0/24 exhausted on {self.node_name}")
            self._in_use.add(n)
            self._leases[pod_key] = n
            self.stats["setups"] += 1
        return f"{self.base}.{n}"

    def adopt(self, pod_key: str, ip: str) -> bool:
        """Seed an existing pod's lease (kubelet restart recovery): a
        fresh plugin must not hand a running pod's address to a new pod.
        Returns False for addresses outside this plugin's range (e.g.
        leased under a pre-CIDR hash base) — those cannot collide with
        this range, so skipping them is safe."""
        prefix = self.base + "."
        if not ip.startswith(prefix):
            return False
        try:
            n = int(ip[len(prefix):])
        except ValueError:
            return False
        # 2-254 only, matching setup_pod's lease range: .1 is the reserved
        # cbr0 bridge address and must never be recorded as a pod lease
        if not 2 <= n <= 254:
            return False
        self._leases[pod_key] = n
        self._in_use.add(n)
        return True

    def teardown_pod(self, pod_key: str) -> None:
        n = self._leases.pop(pod_key, None)
        if n is not None:
            self._in_use.discard(n)
            self.stats["teardowns"] += 1

    def pod_ip(self, pod_key: str) -> Optional[str]:
        n = self._leases.get(pod_key)
        return None if n is None else f"{self.base}.{n}"

    def leased(self) -> set[str]:
        return set(self._leases)

    def status(self) -> dict:
        return {"name": self.name, "cidr": f"{self.base}.0/24",
                "leased": len(self._leases), **self.stats}
