"""Kubelet volume manager: the desired-vs-actual mount state machine.

Capability of ``pkg/kubelet/volumemanager`` (2,546 LoC;
``reconciler/reconciler.go:165``):

- **desired state of world**: every PVC-backed volume of every pod
  assigned to this node must be mounted before that pod may start
  (``WaitForAttachAndMount`` — the hollow kubelet gates Pending→Running
  on it);
- **actual state of world**: a volume mounts only once the attach/detach
  controller has attached its PV to this node
  (``node.status.volumesAttached``), after a configurable mount latency;
- **volumesInUse**: mounted volumes are reported in node status; the
  attach/detach controller MUST NOT detach a volume still in use — the
  unmount-before-detach safety protocol
  (``attachdetach`` reconciler checking volumesInUse);
- pods leaving the node unmount their volumes, releasing them for
  detach.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api import types as api


@dataclass
class _MountState:
    pv_name: str
    mounting_since: Optional[float] = None  # attach seen; latency running
    mounted: bool = False


class VolumeManager:
    def __init__(self, clock: Callable[[], float], mount_latency: float = 0.0):
        self.clock = clock
        self.mount_latency = mount_latency
        # pod key -> {pv name -> state}
        self._pods: dict[str, dict[str, _MountState]] = {}

    # -- desired state ------------------------------------------------------
    def _required_pvs(self, pod: api.Pod, pvc_to_pv: dict[str, str]):
        """PV names this pod needs mounted; None = some claim is unbound
        (nothing mountable yet, and startup must block)."""
        out = []
        for vol in pod.spec.volumes:
            if vol.pvc_name:
                pv = pvc_to_pv.get(f"{pod.meta.namespace}/{vol.pvc_name}")
                if pv is None:
                    return None
                out.append(pv)
        return out

    def sync(self, pods: list[api.Pod], attached: set[str],
             pvc_to_pv: dict[str, str]) -> None:
        """One reconciler pass (reconciler.go:165): progress mounts for
        present pods, unmount volumes of departed pods."""
        now = self.clock()
        live = set()
        for pod in pods:
            # terminal pods unmount like departed ones (the real kubelet
            # tears down volumes of terminated pods so they can detach)
            if pod.status.phase in (api.SUCCEEDED, api.FAILED):
                continue
            required = self._required_pvs(pod, pvc_to_pv)
            if not required:
                continue  # no volumes (or unbound): no state entry at all
            key = pod.meta.key
            live.add(key)
            states = self._pods.setdefault(key, {})
            for pv in required:
                st = states.get(pv)
                if st is None:
                    st = states[pv] = _MountState(pv_name=pv)
                if st.mounted:
                    continue
                if pv not in attached:
                    st.mounting_since = None  # must wait for the attach
                    continue
                if st.mounting_since is None:
                    st.mounting_since = now
                if now - st.mounting_since >= self.mount_latency:
                    st.mounted = True
        for gone in set(self._pods) - live:
            del self._pods[gone]  # unmount everything of departed pods

    # -- queries ------------------------------------------------------------
    def pod_volumes_ready(self, pod: api.Pod, pvc_to_pv: dict[str, str]) -> bool:
        """WaitForAttachAndMount: True when every required volume is
        mounted (pods without PVC volumes are trivially ready)."""
        required = self._required_pvs(pod, pvc_to_pv)
        if required is None:
            return False  # unbound claim blocks startup
        if not required:
            return True
        states = self._pods.get(pod.meta.key, {})
        return all(states.get(pv) is not None and states[pv].mounted for pv in required)

    def has_state(self) -> bool:
        return bool(self._pods)

    def volumes_in_use(self) -> list[str]:
        out = set()
        for states in self._pods.values():
            for pv, st in states.items():
                if st.mounted:
                    out.add(pv)
        return sorted(out)
