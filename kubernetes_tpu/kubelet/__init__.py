"""Node agent layer (hollow kubelet fleet for scale testing; SURVEY.md L7/§4.5)."""

from ..client.informer import PodNodeIndex
from .hollow import HollowFleet, HollowKubelet
