"""hyperkube: the all-in-one multiplexer binary (reference
``cmd/hyperkube``, ``pkg/hyperkube``) — one entry point, every
component:

    python -m kubernetes_tpu apiserver --port 6443 ...
    python -m kubernetes_tpu scheduler --apiserver ...
    python -m kubernetes_tpu controller-manager --apiserver ...
    python -m kubernetes_tpu cloud-controller-manager --apiserver ...
    python -m kubernetes_tpu kubelet --apiserver ...
    python -m kubernetes_tpu kubectl get pods ...
    python -m kubernetes_tpu kubefed join ...
"""

from __future__ import annotations

import sys

COMPONENTS = {
    "apiserver": "kubernetes_tpu.apiserver.__main__",
    "kube-apiserver": "kubernetes_tpu.apiserver.__main__",
    "scheduler": "kubernetes_tpu.scheduler.__main__",
    "kube-scheduler": "kubernetes_tpu.scheduler.__main__",
    "controller-manager": "kubernetes_tpu.controllers.__main__",
    "kube-controller-manager": "kubernetes_tpu.controllers.__main__",
    "cloud-controller-manager": "kubernetes_tpu.cloud.__main__",
    "kubelet": "kubernetes_tpu.kubelet.__main__",
    "kubectl": "kubernetes_tpu.cli.kubectl",
    "kubefed": "kubernetes_tpu.federation.kubefed",
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        sys.stderr.write(
            "usage: python -m kubernetes_tpu COMPONENT [args...]\n"
            "components: " + ", ".join(sorted(set(COMPONENTS))) + "\n")
        return 0 if argv else 2
    component = argv[0]
    mod_name = COMPONENTS.get(component)
    if mod_name is None:
        sys.stderr.write(f"unknown component {component!r}; "
                         f"one of {sorted(set(COMPONENTS))}\n")
        return 2
    import importlib

    mod = importlib.import_module(mod_name)
    return mod.main(argv[1:])


if __name__ == "__main__":
    sys.exit(main())
