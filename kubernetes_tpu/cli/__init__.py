"""CLI layer (kubectl capability; SURVEY.md L8)."""

from .kubectl import Kubectl, main
