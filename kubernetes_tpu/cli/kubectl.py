"""kubectl-equivalent CLI.

Capability of the reference's kubectl core verbs (``pkg/kubectl``, SURVEY.md
§2.8) at the depth this control plane serves:

  get / describe / create -f / apply -f / delete / scale / cordon /
  uncordon / drain / events / top nodes

``apply`` is declarative create-or-update keyed on the last-applied
configuration annotation (the essential of the reference's 3-way strategic
merge, ``cmd/apply.go``): unchanged manifests are left alone, changed ones
update spec/labels while preserving cluster-owned fields.  ``drain``
cordons then evicts (``cmd/drain.go``).  Manifests are YAML or JSON, one or
many documents.

Speaks to an API server over HTTP (``--server``), or to an in-process
clientset when embedded (tests, single-binary demos).
"""

from __future__ import annotations

import argparse
import io
import json
import re
import sys
from typing import Optional

import yaml

from ..api import types as api
from ..api.types import kind_for_plural
from ..client.clientset import Clientset
from ..client.remote import RemoteStore
from ..store.store import AlreadyExistsError, NotFoundError

LAST_APPLIED = "kubectl.kubernetes.io/last-applied-configuration"

# populated from the live subparser table each time main() builds it, so
# `kubectl completion` always reflects the real verb set
ALL_VERBS: list[str] = []


class _AbortMutation(Exception):
    """Raised inside a guaranteed_update mutate to cancel the write: a CLI
    verb that refuses an operation must not commit a no-op revision (a
    spurious MODIFIED event would wake every watcher)."""


class _NoopMutation(Exception):
    """The mutation produced an identical object — report success but skip
    the write (no revision bump, no content-free MODIFIED event)."""


def _update_if_changed(client, name, mutate, namespace):
    """guaranteed_update that aborts when the object comes out unchanged.
    Returns True if a write happened, False on a no-op."""

    def _mutate(obj):
        before = obj.to_dict()
        new = mutate(obj)
        if new.to_dict() == before:
            raise _NoopMutation
        return new

    try:
        client.guaranteed_update(name, _mutate, namespace)
        return True
    except _NoopMutation:
        return False


def _build_subjects(users, groups, serviceaccounts):
    """RBAC subject list from --user/--group/--serviceaccount flags,
    deduplicated, ns:name both halves required (shared by create
    rolebinding/clusterrolebinding and set subject — upstream
    set_subject.go validates identically).  Returns (subjects, error):
    exactly one is non-None."""
    from ..api.rbac import Subject

    subjects: list = []
    seen: set = set()

    def _add(s):
        ident = (s.kind, s.name, s.namespace)
        if ident not in seen:
            seen.add(ident)
            subjects.append(s)

    for u in users:
        _add(Subject(kind="User", name=u))
    for g in groups:
        _add(Subject(kind="Group", name=g))
    for sa in serviceaccounts:
        sa_ns, _, sa_name = sa.partition(":")
        if not sa_ns or not sa_name:
            return None, f"error: --serviceaccount wants ns:name, got {sa!r}\n"
        _add(Subject(kind="ServiceAccount", name=sa_name, namespace=sa_ns))
    if not subjects:
        return None, ("error: at least one of --user/--group/"
                      "--serviceaccount is required\n")
    return subjects, None


def _parse_selector(spec: str):
    """kubectl's selector grammar — the SAME parser the wire API uses
    (``api.selectors.parse_selector_string``: equality, set-based ``in``/
    ``notin``, exists), so ``-l`` accepts exactly what
    ``?labelSelector=`` does.  Returns a LabelSelector or None on a
    malformed (or effectively empty) selector — an empty selector must
    NOT silently mean match-all, because delete -l rides on it."""
    from ..api.selectors import parse_selector_string

    try:
        return parse_selector_string(spec)
    except ValueError:
        return None


def _labels_match(obj, want) -> bool:
    return want.matches(obj.meta.labels)


_LABEL_VALUE_RE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")


def _valid_label_value(v: str) -> bool:
    """``validation.IsValidLabelValue``: ≤63 chars, empty allowed,
    alphanumeric ends with -_. allowed in the middle (label.go
    validates values at parse time; annotate does not)."""
    return len(v) <= 63 and bool(_LABEL_VALUE_RE.match(v))
REVISION_ANNOTATION = api.DEPLOYMENT_REVISION_ANNOTATION


def _jsonpath(doc, expr: str) -> list:
    """The jsonpath subset ``get -o jsonpath=`` actually gets used for
    (reference ``pkg/util/jsonpath``): ``{.a.b}``, ``{.items[2].x}``, and
    ``{.items[*].x}`` fan-out.  Multiple ``{...}`` groups concatenate."""
    import re

    out: list = []
    exprs = re.findall(r"\{([^}]*)\}", expr) or [expr]
    for e in exprs:
        nodes = [doc]
        for part in [p for p in e.strip().lstrip(".").split(".") if p]:
            m = re.fullmatch(r"([^\[\]]*)(?:\[(\*|-?\d+)\])?", part)
            if m is None:
                raise ValueError(f"bad jsonpath segment {part!r}")
            field_name, idx = m.group(1), m.group(2)
            next_nodes = []
            for n in nodes:
                v = n[field_name] if field_name else n
                if idx is None:
                    next_nodes.append(v)
                elif idx == "*":
                    next_nodes.extend(v)
                else:
                    next_nodes.append(v[int(idx)])
            nodes = next_nodes
        out.extend(nodes)
    return out

# kind -> plural resource name, from the one type registry (RESTMapper
# analogue) — new kinds (incl. CRDs) become kubectl-addressable on import.
KIND_TO_RESOURCE = api.KIND_PLURALS

_SHORT_NAMES = {
    "po": "pods",
    "no": "nodes",
    "svc": "services",
    "rs": "replicasets",
    "rc": "replicationcontrollers",
    "deploy": "deployments",
    "netpol": "networkpolicies",
    "ev": "events",
    "ns": "namespaces",
    "ds": "daemonsets",
    "sts": "statefulsets",
    "cj": "cronjobs",
    "hpa": "horizontalpodautoscalers",
    "pdb": "poddisruptionbudgets",
    "pv": "persistentvolumes",
    "pvc": "persistentvolumeclaims",
    "sa": "serviceaccounts",
    "quota": "resourcequotas",
    "cm": "configmaps",
    "ep": "endpoints",
    "limits": "limitranges",
    "pc": "priorityclasses",
    "csr": "certificatesigningrequests",
}


def _resource_aliases() -> dict[str, str]:
    """plural, singular (kind lowercased), and short names all resolve."""
    out = dict(_SHORT_NAMES)
    for kind, plural in KIND_TO_RESOURCE.items():
        out[plural] = plural
        out[kind.lower()] = plural
    return out


def _resolve(resource: str):
    # Alias -> (plural, kind), computed per call so kinds registered
    # after module import (CRD-style) resolve immediately.
    plural = _resource_aliases().get(resource, resource)
    return plural, kind_for_plural(plural)


class Kubectl:
    def __init__(self, clientset: Clientset, out=None):
        self.cs = clientset
        self.out = out or sys.stdout

    def _print(self, *cols_rows) -> None:
        rows = [r for r in cols_rows]
        widths = [max(len(str(r[i])) for r in rows) for i in range(len(rows[0]))]
        for r in rows:
            self.out.write("  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip() + "\n")

    # -- get --watch --------------------------------------------------------
    def get_watch(self, resource: str, namespace: Optional[str] = None,
                  selector: str = "", timeout: float = 30.0) -> int:
        """``kubectl get RES -w``: print the current table, then stream
        event rows until ``timeout`` (the reference streams forever;
        bounded here so scripts and tests terminate)."""
        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return 1
        want = None
        if selector:
            want = _parse_selector(selector)
            if want is None:
                self.out.write(f"error: bad selector {selector!r}\n")
                return 1
        client = self.cs.client_for(kind)
        ns_scope = namespace if namespace is not None else client.default_namespace
        # LIST at a revision, then WATCH strictly after it: events landing
        # between the table and the stream are never lost
        rev = self._print_table(kind, client, ns_scope, want)
        watch = self.cs.store.watch(kind, from_revision=rev)
        import time as _time

        deadline = _time.monotonic() + timeout
        try:
            while _time.monotonic() < deadline:
                ev = watch.get(timeout=min(0.5, max(0.0, deadline - _time.monotonic())))
                if ev is None:
                    continue
                from ..store.store import WATCH_GAP

                if ev.type == WATCH_GAP:
                    # the stream lost continuity (410 on resume): relist
                    # like the reflector does — reprint the table at the
                    # fresh revision and watch on from there
                    watch.stop()
                    rev = self._print_table(kind, client, ns_scope, want)
                    watch = self.cs.store.watch(kind, from_revision=rev)
                    continue
                obj = api.from_dict(ev.object) if isinstance(ev.object, dict) else ev.object
                # the stream scopes like the table: one namespace (unless
                # the kind is cluster-scoped, where ns is always "")
                if (kind not in api.CLUSTER_SCOPED_KINDS
                        and obj.meta.namespace != ns_scope):
                    continue
                if want is not None and not _labels_match(obj, want):
                    continue
                row = self._row(kind, obj)
                self.out.write(f"{ev.type:<9} " + "  ".join(str(c) for c in row) + "\n")
        finally:
            watch.stop()
        return 0

    def _print_table(self, kind, client, ns_scope, want) -> int:
        """List + filter + print the table; returns the list revision
        (shared by ``get`` and ``get -w``)."""
        objs, rev = client.list(ns_scope)
        if want is not None:
            objs = [o for o in objs if _labels_match(o, want)]
        rows = [self._headers(kind)] + [self._row(kind, o) for o in objs]
        self._print(*rows)
        return rev

    # -- get ---------------------------------------------------------------
    def get(self, resource: str, name: Optional[str] = None, namespace: Optional[str] = None,
            output: str = "", selector: str = "", sort_by: str = "",
            show_labels: bool = False, no_headers: bool = False) -> int:
        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return 1
        client = self.cs.client_for(kind)
        if name:
            if selector:
                self.out.write("error: a name cannot be combined with -l\n")
                return 1
            try:
                objs = [client.get(name, namespace)]
            except NotFoundError:
                self.out.write(f'Error: {resource} "{name}" not found\n')
                return 1
        else:
            objs, _ = client.list(namespace)
            if selector:
                want = _parse_selector(selector)
                if want is None:
                    self.out.write(f"error: bad selector {selector!r}\n")
                    return 1
                objs = [o for o in objs if _labels_match(o, want)]
        if sort_by:
            # --sort-by '{.spec.nodeName}' (pkg/kubectl sorting_printer.go):
            # numbers sort numerically, everything else as strings
            def _sort_key(vals):
                v = vals[0] if vals else ""
                if isinstance(v, bool):
                    return (1, str(v))
                if isinstance(v, (int, float)):
                    return (0, v)
                try:
                    return (0, float(v))
                except (TypeError, ValueError):
                    return (1, str(v))

            try:
                keyed = [(_sort_key(_jsonpath(o.to_dict(), sort_by)), o)
                         for o in objs]
            except (KeyError, IndexError, TypeError, ValueError) as e:
                self.out.write(f"error: sort-by: {e}\n")
                return 1
            objs = [o for _, o in sorted(keyed, key=lambda kv: kv[0])]
        if output.startswith("custom-columns="):
            # -o custom-columns=HDR:.path,HDR2:.path (custom_column_printer)
            spec = output[len("custom-columns="):]
            cols = []
            for part in spec.split(","):
                hdr, _, path = part.partition(":")
                if not hdr or not path:
                    self.out.write(f"error: bad custom-columns spec {part!r}\n")
                    return 1
                cols.append((hdr, path))
            rows = [] if no_headers else [tuple(h for h, _ in cols)]
            for o in objs:
                doc = o.to_dict()
                row = []
                for _, path in cols:
                    try:
                        vals = _jsonpath(doc, "{" + path + "}")
                        row.append(",".join(str(v) for v in vals) or "<none>")
                    except (KeyError, IndexError, TypeError, ValueError):
                        row.append("<none>")
                rows.append(tuple(row))
            if rows:
                self._print(*rows)
            return 0
        if output == "json":
            docs = [o.to_dict() for o in objs]
            self.out.write(json.dumps(docs[0] if name else {"items": docs}, indent=2) + "\n")
            return 0
        if output == "yaml":
            docs = [o.to_dict() for o in objs]
            self.out.write(yaml.safe_dump(docs[0] if name else {"items": docs}))
            return 0
        if output and output != "wide" and not output.startswith("jsonpath="):
            self.out.write(f"error: unsupported output format {output!r}\n")
            return 1
        if output.startswith("jsonpath="):
            docs = [o.to_dict() for o in objs]
            doc = docs[0] if name else {"items": docs}
            try:
                values = _jsonpath(doc, output[len("jsonpath="):])
            except (KeyError, IndexError, TypeError, ValueError) as e:
                self.out.write(f"error: jsonpath: {e}\n")
                return 1
            self.out.write(" ".join(str(v) for v in values) + "\n")
            return 0
        wide = output == "wide"
        header = self._headers(kind)
        if wide:
            header = header + self._wide_headers(kind)
        if show_labels:
            header = header + ("LABELS",)
        rows = [] if no_headers else [header]
        for o in objs:
            row = self._row(kind, o)
            if wide:
                row = row + self._wide_row(kind, o)
            if show_labels:
                row = row + (",".join(f"{k}={v}" for k, v in sorted(o.meta.labels.items()))
                             or "<none>",)
            rows.append(row)
        if rows:
            self._print(*rows)
        return 0

    def _wide_headers(self, kind: str):
        return {"Pod": ("IP",), "Node": ("ADDRESSES", "CIDR"),
                "Service": ("CLUSTER-IP", "PORTS")}.get(kind, ())

    def _wide_row(self, kind: str, o):
        if kind == "Pod":
            return (o.status.pod_ip or "<none>",)
        if kind == "Node":
            addrs = ",".join(a.get("address", "") for a in o.status.addresses)
            return (addrs or "<none>", o.spec.pod_cidr or "<none>")
        if kind == "Service":
            ports = ",".join(str(p.port) for p in o.ports)
            return (o.cluster_ip or "<none>", ports or "<none>")
        return ()

    def _headers(self, kind: str):
        return {
            "Pod": ("NAME", "STATUS", "NODE", "PRIORITY"),
            "Node": ("NAME", "READY", "UNSCHEDULABLE", "CPU", "MEMORY"),
            "Deployment": ("NAME", "DESIRED", "CURRENT", "UP-TO-DATE", "READY"),
            "ReplicaSet": ("NAME", "DESIRED", "CURRENT", "READY"),
            "Service": ("NAME", "SELECTOR"),
            "Event": ("OBJECT", "TYPE", "REASON", "MESSAGE"),
            "Job": ("NAME", "ACTIVE", "SUCCEEDED", "FAILED"),
            "DaemonSet": ("NAME", "DESIRED", "CURRENT", "READY"),
            "StatefulSet": ("NAME", "DESIRED", "CURRENT", "READY"),
            "Namespace": ("NAME", "STATUS"),
        }.get(kind, ("NAME",))

    def _row(self, kind: str, o):
        if kind == "Pod":
            return (o.meta.name, o.status.phase, o.spec.node_name or "<none>", o.spec.priority)
        if kind == "Node":
            ready = o.status.condition(api.NODE_READY)
            return (
                o.meta.name,
                ready.status if ready else "Unknown",
                o.spec.unschedulable,
                str(o.status.allocatable.get(api.CPU, "")),
                str(o.status.allocatable.get(api.MEMORY, "")),
            )
        if kind == "Deployment":
            return (o.meta.name, o.replicas, o.status_replicas, o.status_updated_replicas,
                    o.status_ready_replicas)
        if kind == "ReplicaSet":
            return (o.meta.name, o.replicas, o.status_replicas, o.status_ready_replicas)
        if kind == "Service":
            return (o.meta.name, ",".join(f"{k}={v}" for k, v in o.selector.items()))
        if kind == "Event":
            return (o.involved_key, o.type, o.reason, o.message[:80])
        if kind == "Job":
            return (o.meta.name, o.status_active, o.status_succeeded, o.status_failed)
        if kind == "DaemonSet":
            return (o.meta.name, o.status_desired, o.status_current, o.status_ready)
        if kind == "StatefulSet":
            return (o.meta.name, o.replicas, o.status_current_replicas, o.status_ready_replicas)
        if kind == "Namespace":
            return (o.meta.name, o.phase)
        return (o.meta.name,)

    # -- describe (pkg/kubectl describe.go: per-kind describers) -----------
    def describe(self, resource: str, name: str, namespace: Optional[str] = None) -> int:
        resource, kind = _resolve(resource)
        try:
            obj = self.cs.client_for(kind).get(name, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        describer = {"Pod": self._describe_pod, "Node": self._describe_node,
                     "Deployment": self._describe_deployment,
                     "Service": self._describe_service}.get(kind)
        if describer is not None:
            describer(obj)
        else:
            self.out.write(yaml.safe_dump(obj.to_dict(), sort_keys=False))
        events, _ = self.cs.events.list()
        related = [e for e in events if e.involved_key.endswith(f"/{name}") or e.involved_key == name]
        if related:
            self.out.write("Events:\n")
            for e in related[-10:]:
                self.out.write(f"  {e.type}\t{e.reason}\t{e.message}\n")
        return 0

    def _kv(self, key: str, value) -> None:
        self.out.write(f"{key + ':':<22}{value}\n")

    def _labels_line(self, labels: dict) -> str:
        return ",".join(f"{k}={v}" for k, v in sorted(labels.items())) or "<none>"

    def _describe_pod(self, pod) -> None:
        self._kv("Name", pod.meta.name)
        self._kv("Namespace", pod.meta.namespace)
        self._kv("Node", pod.spec.node_name or "<none>")
        self._kv("Labels", self._labels_line(pod.meta.labels))
        self._kv("Annotations", self._labels_line(pod.meta.annotations))
        self._kv("Status", pod.status.phase)
        self._kv("IP", pod.status.pod_ip or "<none>")
        if pod.spec.priority:
            self._kv("Priority", pod.spec.priority)
        self.out.write("Containers:\n")
        statuses = {s.name: s for s in pod.status.container_statuses}
        for c in pod.spec.containers:
            self.out.write(f"  {c.name}:\n")
            self.out.write(f"    Image:    {c.image or '<none>'}\n")
            req = ", ".join(f"{k}={v}" for k, v in c.resources.requests.items())
            if req:
                self.out.write(f"    Requests: {req}\n")
            st = statuses.get(c.name)
            if st is not None:
                self.out.write(f"    Ready:    {st.ready}\n")
                self.out.write(f"    Restarts: {st.restart_count}\n")
        if pod.spec.tolerations:
            tols = "; ".join(f"{t.key or '<all>'}:{t.effect or '<all>'}"
                             for t in pod.spec.tolerations)
            self._kv("Tolerations", tols)
        conds = [f"{c.get('type')}={c.get('status')}" for c in pod.status.conditions]
        if conds:
            self._kv("Conditions", ", ".join(conds))

    def _describe_node(self, node) -> None:
        self._kv("Name", node.meta.name)
        self._kv("Labels", self._labels_line(node.meta.labels))
        self._kv("Unschedulable", node.spec.unschedulable)
        if node.spec.taints:
            self._kv("Taints", "; ".join(
                f"{t.key}={t.value}:{t.effect}" for t in node.spec.taints))
        if node.spec.pod_cidr:
            self._kv("PodCIDR", node.spec.pod_cidr)
        conds = [f"{c.type}={c.status}" for c in node.status.conditions]
        self._kv("Conditions", ", ".join(conds) or "<none>")
        self._kv("Capacity", ", ".join(
            f"{k}={v}" for k, v in node.status.capacity.items()))
        self._kv("Allocatable", ", ".join(
            f"{k}={v}" for k, v in node.status.allocatable.items()))
        pods = [p for p in self.cs.pods.list()[0]
                if p.spec.node_name == node.meta.name]
        self.out.write(f"Non-terminated Pods:  ({len(pods)} in total)\n")
        for p in pods[:20]:
            self.out.write(f"  {p.meta.namespace}/{p.meta.name}  {p.status.phase}\n")

    def _describe_deployment(self, dep) -> None:
        self._kv("Name", dep.meta.name)
        self._kv("Namespace", dep.meta.namespace)
        self._kv("Selector", self._labels_line(dep.selector.match_labels))
        self._kv("Replicas", f"{dep.replicas} desired | "
                             f"{dep.status_updated_replicas} updated | "
                             f"{dep.status_replicas} total | "
                             f"{dep.status_ready_replicas} ready")
        self._kv("StrategyType", dep.strategy)
        if dep.strategy == "RollingUpdate":
            self._kv("RollingUpdateStrategy",
                     f"{dep.max_unavailable} max unavailable, "
                     f"{dep.max_surge} max surge")
        images = ", ".join(c.image for c in dep.template.spec.containers if c.image)
        self._kv("Pod Template Image", images or "<none>")
        rses = [rs for rs in self.cs.replicasets.list(dep.meta.namespace)[0]
                if (ref := rs.meta.controller_ref()) is not None
                and ref.uid == dep.meta.uid]
        if rses:
            self._kv("ReplicaSets", ", ".join(
                f"{rs.meta.name} ({rs.status_replicas}/{rs.replicas})"
                for rs in rses))

    def _describe_service(self, svc) -> None:
        self._kv("Name", svc.meta.name)
        self._kv("Namespace", svc.meta.namespace)
        self._kv("Selector", self._labels_line(svc.selector))
        self._kv("Type", svc.type)
        self._kv("IP", svc.cluster_ip or "<none>")
        if svc.status_load_balancer:
            self._kv("LoadBalancer Ingress", ", ".join(svc.status_load_balancer))
        for p in svc.ports:
            self._kv("Port", f"{p.name or '<unset>'}  {p.port}/{p.protocol}"
                             + (f" -> {p.target_port}" if p.target_port else ""))
        try:
            eps = self.cs.endpoints.get(svc.meta.name, svc.meta.namespace)
            addrs = [f"{a.ip}:{p.port}" for s in eps.subsets
                     for a in s.addresses for p in s.ports]
            self._kv("Endpoints", ", ".join(addrs) or "<none>")
        except (NotFoundError, KeyError):
            self._kv("Endpoints", "<none>")

    # -- create / apply / delete ------------------------------------------
    def _load_manifests(self, path: str) -> list[dict]:
        from ..api.scheme import convert_to_internal

        text = sys.stdin.read() if path == "-" else open(path).read()
        # versioned wire documents (apps/v1beta1, extensions/v1beta1,
        # batch/v2alpha1, ...) decode through the scheme — reference-era
        # YAML applies unchanged
        return [convert_to_internal(d) for d in yaml.safe_load_all(text) if d]

    def create(self, filename: str) -> int:
        from ..admission.framework import AdmissionDenied
        from ..client.remote import ForbiddenError

        rc = 0
        for doc in self._load_manifests(filename):
            kind = doc.get("kind", "")
            if kind not in KIND_TO_RESOURCE:
                self.out.write(f"error: unknown kind {kind!r} in manifest\n")
                rc = 1
                continue
            try:
                obj = self.cs.client_for(kind).create(api.from_dict(doc))
                self.out.write(f"{KIND_TO_RESOURCE[kind]}/{obj.meta.name} created\n")
            except AlreadyExistsError:
                self.out.write(f"Error: {kind} already exists\n")
                rc = 1
            except (AdmissionDenied, ForbiddenError) as e:
                # the reference surfaces admission/authz denials as
                # "Error from server (Forbidden)" — in-proc raises
                # AdmissionDenied, the wire raises ForbiddenError (403)
                self.out.write(f"Error from server (Forbidden): {e}\n")
                rc = 1
        return rc

    def apply(self, filename: str, prune: bool = False,
              selector: str = "") -> int:
        """Declarative apply; with ``--prune -l selector``, objects that
        carry the last-applied annotation, match the selector, and are
        ABSENT from the manifest set are deleted (cmd/apply.go prune —
        same guard rails: never touches objects apply didn't create)."""
        applied: set[tuple[str, str, str]] = set()  # (kind, ns, name)
        want = None
        if prune:
            if not selector:
                self.out.write("error: --prune requires -l selector\n")
                return 1
            want = _parse_selector(selector)
            if want is None:
                self.out.write(f"error: bad selector {selector!r}\n")
                return 1
        for doc in self._load_manifests(filename):
            kind = doc.get("kind", "")
            if kind not in KIND_TO_RESOURCE:
                self.out.write(f"error: unknown kind {kind!r} in manifest\n")
                return 1
            client = self.cs.client_for(kind)
            manifest = json.dumps(doc, sort_keys=True)
            meta = doc.get("metadata") or {}
            name = meta.get("name", "")
            ns = meta.get("namespace", client.default_namespace)
            applied.add((kind, ns, name))
            try:
                cur = client.get(name, ns)
            except (NotFoundError, KeyError):
                obj = api.from_dict(doc)
                obj.meta.annotations[LAST_APPLIED] = manifest
                client.create(obj)
                self.out.write(f"{KIND_TO_RESOURCE[kind]}/{name} created\n")
                continue
            if cur.meta.annotations.get(LAST_APPLIED) == manifest:
                self.out.write(f"{KIND_TO_RESOURCE[kind]}/{name} unchanged\n")
                continue

            def _merge(live):
                desired = api.from_dict(doc)
                desired.meta = live.meta  # preserve cluster-owned identity
                desired.meta.labels = dict((doc.get("metadata") or {}).get("labels") or {})
                desired.meta.annotations = dict(live.meta.annotations)
                desired.meta.annotations[LAST_APPLIED] = manifest
                if hasattr(live, "status"):
                    desired.status = live.status  # status is cluster-owned
                return desired

            client.guaranteed_update(name, _merge, ns)
            self.out.write(f"{KIND_TO_RESOURCE[kind]}/{name} configured\n")
        if want is not None:
            self._prune(applied, want)
        return 0

    def _prune(self, applied: set, want) -> None:
        """Delete previously-applied, selector-matching objects absent
        from this apply set.  Scope: every kind that appeared in the
        manifests, and for namespaced kinds ONLY the namespaces the
        manifests touched — pruning is destructive, so it must never
        reach into a namespace the apply set never mentioned (the
        reference's prune visits only the apply set's namespaces;
        cluster-scoped kinds have no namespace guard)."""
        namespaces = sorted({ns for _, ns, _ in applied})
        for kind in {k for k, _, _ in applied}:
            client = self.cs.client_for(kind)
            scopes = [None] if kind in api.CLUSTER_SCOPED_KINDS else namespaces
            for scope in scopes:
                for obj in client.list(scope)[0]:
                    ident = (kind, obj.meta.namespace, obj.meta.name)
                    if ident in applied:
                        continue
                    if LAST_APPLIED not in obj.meta.annotations:
                        continue  # apply never owned it; never prune it
                    if not _labels_match(obj, want):
                        continue
                    try:
                        client.delete(obj.meta.name, obj.meta.namespace)
                    except NotFoundError:
                        continue
                    self.out.write(
                        f"{KIND_TO_RESOURCE[kind]}/{obj.meta.name} pruned\n")

    # -- apply *-last-applied (cmd/apply_{view,set,edit}_last_applied.go) --
    def _get_for_last_applied(self, resource: str, name: str,
                              namespace: Optional[str]):
        """(client, obj, display) or None after writing the error."""
        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return None
        client = self.cs.client_for(kind)
        try:
            return client, client.get(name, namespace), f"{resource}/{name}"
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return None

    def apply_view_last_applied(self, resource: str, name: str,
                                namespace: Optional[str] = None,
                                output: str = "yaml") -> int:
        if output not in ("yaml", "json"):
            self.out.write(f"error: unexpected -o output mode {output!r} "
                           f"(yaml|json)\n")
            return 1
        got = self._get_for_last_applied(resource, name, namespace)
        if got is None:
            return 1
        _, obj, display = got
        raw = obj.meta.annotations.get(LAST_APPLIED)
        if raw is None:
            self.out.write(
                f"error: no last-applied-configuration annotation found on "
                f"{display}\n")
            return 1
        doc = json.loads(raw)
        if output == "json":
            self.out.write(json.dumps(doc, indent=2, sort_keys=True) + "\n")
        else:
            self.out.write(yaml.safe_dump(doc, sort_keys=False))
        return 0

    def _write_last_applied(self, client, name: str, ns, manifest: str) -> None:
        """Annotation write through the no-op guard: an unchanged
        annotation must not commit a revision and wake every watcher."""

        def _set(live):
            live.meta.annotations[LAST_APPLIED] = manifest
            return live

        _update_if_changed(client, name, _set, ns)

    def apply_set_last_applied(self, filename: str,
                               create_annotation: bool = False) -> int:
        """Overwrite each manifest object's last-applied annotation with
        the file's content; absent annotations are an error unless
        --create-annotation (the reference's guard: set-last-applied on
        an object apply never owned is usually a mistake)."""
        try:
            docs = self._load_manifests(filename)  # scheme-converted, like apply
        except (OSError, yaml.YAMLError) as e:
            self.out.write(f"error: {e}\n")
            return 1
        for doc in docs:
            kind = doc.get("kind", "")
            if kind not in KIND_TO_RESOURCE:
                self.out.write(f"error: unknown kind {kind!r}\n")
                return 1
            meta = doc.get("metadata") or {}
            name = meta.get("name", "")
            client = self.cs.client_for(kind)
            ns = meta.get("namespace", client.default_namespace)
            manifest = json.dumps(doc, sort_keys=True)
            try:
                cur = client.get(name, ns)
            except (NotFoundError, KeyError):
                self.out.write(f'Error: {KIND_TO_RESOURCE[kind]} "{name}" '
                               f'not found\n')
                return 1
            if LAST_APPLIED not in cur.meta.annotations and not create_annotation:
                self.out.write(
                    f"error: {KIND_TO_RESOURCE[kind]}/{name} has no "
                    f"last-applied-configuration annotation; use "
                    f"--create-annotation to set one\n")
                return 1
            self._write_last_applied(client, name, ns, manifest)
            self.out.write(f"{KIND_TO_RESOURCE[kind]}/{name} configured\n")
        return 0

    def apply_edit_last_applied(self, resource: str, name: str,
                                namespace: Optional[str] = None) -> int:
        """annotation -> $EDITOR -> annotation (never touches the live
        spec; the next apply's 3-way merge consumes the edit)."""
        import os
        import subprocess
        import tempfile

        got = self._get_for_last_applied(resource, name, namespace)
        if got is None:
            return 1
        client, obj, display = got
        raw = obj.meta.annotations.get(LAST_APPLIED)
        if raw is None:
            self.out.write(
                f"error: no last-applied-configuration annotation found on "
                f"{display}\n")
            return 1
        editor = os.environ.get("EDITOR", "vi")
        with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
            yaml.safe_dump(json.loads(raw), f, sort_keys=False)
            tmp = f.name
        try:
            rc = subprocess.run([*editor.split(), tmp]).returncode
        except OSError as e:
            os.unlink(tmp)
            self.out.write(f"error: cannot run editor {editor!r}: {e}\n")
            return 1
        if rc != 0:
            os.unlink(tmp)
            self.out.write("Edit cancelled\n")
            return 1
        try:
            edited = yaml.safe_load(open(tmp).read())
        except yaml.YAMLError as e:
            self.out.write(f"error: edited file is not valid YAML: {e}\n"
                           f"your changes are preserved in {tmp}\n")
            return 1
        if not isinstance(edited, dict) or not edited:
            self.out.write(f"error: edited content must be a non-empty "
                           f"mapping; your changes are preserved in {tmp}\n")
            return 1
        original = json.loads(raw)
        if edited.get("kind") != original.get("kind") or (
                (edited.get("metadata") or {}).get("name")
                != (original.get("metadata") or {}).get("name")):
            self.out.write(
                f"error: kind and metadata.name may not change in "
                f"edit-last-applied; your changes are preserved in {tmp}\n")
            return 1
        os.unlink(tmp)
        self._write_last_applied(client, name, obj.meta.namespace,
                                 json.dumps(edited, sort_keys=True))
        self.out.write(f"{display} edited\n")
        return 0

    def delete(self, resource: str, name: Optional[str], namespace: Optional[str] = None,
               selector: str = "", cascade: str = "background") -> int:
        if name and selector:
            self.out.write("error: a name cannot be combined with -l\n")
            return 1
        if selector and not name:
            resource2, kind = _resolve(resource)
            if kind is None:
                self.out.write(f"error: unknown resource {resource!r}\n")
                return 1
            want = _parse_selector(selector)
            if want is None:
                self.out.write(f"error: bad selector {selector!r}\n")
                return 1
            client = self.cs.client_for(kind)
            # scope like every other verb: the default namespace, never
            # all-namespaces implicitly (delete is irreversible)
            ns_scope = namespace if namespace is not None else client.default_namespace
            victims = [o for o in client.list(ns_scope)[0] if _labels_match(o, want)]
            for o in victims:
                try:
                    client.delete(o.meta.name, o.meta.namespace)
                    self.out.write(f"{resource2}/{o.meta.name} deleted\n")
                except NotFoundError:
                    pass
            if not victims:
                self.out.write("No resources found\n")
            return 0
        return self._delete_one(resource, name, namespace, cascade)

    def _delete_one(self, resource: str, name: str, namespace: Optional[str] = None,
                    cascade: str = "background") -> int:
        resource, kind = _resolve(resource)
        client = self.cs.client_for(kind)
        try:
            if cascade == "orphan":
                # the orphan finalizer makes the GC release dependents
                # instead of cascading (graph_builder orphanDependents)
                def _mark(obj):
                    if "orphan" not in obj.meta.finalizers:
                        obj.meta.finalizers.append("orphan")
                    return obj

                client.guaranteed_update(name, _mark, namespace)
            client.delete(name, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} deleted\n")
        return 0

    def top_pods(self, namespace: Optional[str] = None) -> int:
        """``kubectl top pods``: per-pod memory from each node's kubelet
        stats endpoint (the heapster/metricsutil path at this depth)."""
        import json as _json
        import urllib.request

        from concurrent.futures import ThreadPoolExecutor

        rows = [("NAME", "NODE", "MEMORY")]
        ns = namespace or "default"
        nodes = [n for n in self.cs.nodes.list()[0] if n.status.kubelet_url]

        def fetch(node):
            try:
                with urllib.request.urlopen(
                    f"{node.status.kubelet_url}/stats/summary", timeout=5
                ) as r:
                    return node, _json.loads(r.read()), None
            except Exception as e:  # noqa: BLE001 - reported per node below
                return node, None, e

        unreachable = []
        with ThreadPoolExecutor(max_workers=16) as pool:
            for node, summary, err in pool.map(fetch, nodes):
                if err is not None:
                    unreachable.append((node.meta.name, err))
                    continue
                for entry in summary.get("pods", []):
                    ref = entry.get("podRef") or {}
                    if ref.get("namespace") != ns:
                        continue
                    mib = (entry.get("memory") or {}).get("usageBytes", 0) // (1 << 20)
                    rows.append((ref.get("name", ""), node.meta.name, f"{mib}Mi"))
        self._print(*rows)
        for name, err in unreachable:
            self.out.write(f"warning: could not fetch stats from node {name}: {err}\n")
        return 0 if len(rows) > 1 or not unreachable else 1

    # -- rollout (cmd/rollout, rollback.go) --------------------------------
    def _dep_and_rses(self, name: str, namespace: Optional[str]):
        dep = self.cs.deployments.get(name, namespace)
        rses = []
        for rs in self.cs.replicasets.list(namespace or "default")[0]:
            ref = rs.meta.controller_ref()
            if ref is not None and ref.kind == "Deployment" and ref.uid == dep.meta.uid:
                rses.append(rs)
        return dep, rses

    def rollout_status(self, name: str, namespace: Optional[str] = None) -> int:
        """``kubectl rollout status deployment NAME``: 0 when the rollout
        is complete, 1 while in progress (the reference polls; one shot
        here — loops live in the caller)."""
        try:
            dep, _ = self._dep_and_rses(name, namespace)
        except NotFoundError:
            self.out.write(f'Error: deployment "{name}" not found\n')
            return 1
        # completion also requires the CURRENT template's RS to be fully
        # rolled out — aggregate counters alone go stale the instant the
        # spec changes (reference guards with observedGeneration +
        # updatedReplicas-of-current-template)
        from ..controllers.deployment import template_hash

        want_hash = template_hash(dep.template)
        cur_rs = next(
            (rs for rs in self._dep_and_rses(name, namespace)[1]
             if rs.meta.labels.get("pod-template-hash") == want_hash),
            None,
        )
        if (
            cur_rs is not None
            and cur_rs.status_ready_replicas >= dep.replicas
            and dep.status_updated_replicas >= dep.replicas
            and dep.status_ready_replicas >= dep.replicas
            and dep.status_replicas == dep.replicas
        ):
            self.out.write(f'deployment "{name}" successfully rolled out\n')
            return 0
        self.out.write(
            f"Waiting for rollout: {dep.status_updated_replicas} of "
            f"{dep.replicas} updated, {dep.status_ready_replicas} ready\n"
        )
        return 1

    def rollout_history(self, name: str, namespace: Optional[str] = None) -> int:
        try:
            dep, rses = self._dep_and_rses(name, namespace)
        except NotFoundError:
            self.out.write(f'Error: deployment "{name}" not found\n')
            return 1
        self.out.write(f"deployment/{name}\nREVISION  REPLICASET\n")
        for rs in sorted(
            rses, key=lambda r: int(r.meta.annotations.get(REVISION_ANNOTATION, "0"))
        ):
            rev = rs.meta.annotations.get(REVISION_ANNOTATION, "0")
            self.out.write(f"{rev:<9} {rs.meta.name}\n")
        return 0

    def rollout_pause(self, name: str, pause: bool,
                      namespace: Optional[str] = None) -> int:
        """``kubectl rollout pause|resume`` (cmd/rollout/rollout_pause.go):
        flip spec.paused; the deployment controller reconciles scale but
        freezes rollout progress while paused."""
        def _mutate(dep):
            if dep.paused == pause:
                raise _AbortMutation
            dep.paused = pause
            return dep

        verb = "paused" if pause else "resumed"
        try:
            _update_if_changed(self.cs.deployments, name, _mutate, namespace)
        except _AbortMutation:
            self.out.write(f"error: deployment/{name} is already {verb}\n")
            return 1
        except (NotFoundError, KeyError):
            self.out.write(f'Error: deployment "{name}" not found\n')
            return 1
        self.out.write(f"deployment/{name} {verb}\n")
        return 0

    def rollout_undo(self, name: str, namespace: Optional[str] = None,
                     to_revision: int = 0) -> int:
        """``rollback.go``: re-apply the target revision's template (the
        previous one by default); the controller's hash matching then
        treats that RS as new again and bumps its revision."""
        try:
            dep, rses = self._dep_and_rses(name, namespace)
        except NotFoundError:
            self.out.write(f'Error: deployment "{name}" not found\n')
            return 1
        by_rev = {
            int(rs.meta.annotations.get(REVISION_ANNOTATION, "0")): rs for rs in rses
        }
        if not by_rev:
            self.out.write("error: no rollout history\n")
            return 1
        if to_revision:
            target = by_rev.get(to_revision)
            if target is None:
                self.out.write(f"error: revision {to_revision} not found\n")
                return 1
        else:
            revs = sorted(by_rev)
            if len(revs) < 2:
                self.out.write("error: no previous revision\n")
                return 1
            target = by_rev[revs[-2]]

        template = api.PodTemplateSpec.from_dict(target.template.to_dict())
        template.labels.pop("pod-template-hash", None)

        def _rollback(cur):
            cur.template = template
            return cur

        self.cs.deployments.guaranteed_update(name, _rollback, namespace)
        self.out.write(f"deployment/{name} rolled back\n")
        return 0

    def _kubelet_target(self, name: str, ns: str, container: str):
        """In-proc path resolution: pod -> node -> kubelet URL + container.
        Returns (url_base, container) or None after printing the error."""
        try:
            pod = self.cs.pods.get(name, ns)
        except NotFoundError:
            self.out.write(f'Error: pod "{name}" not found\n')
            return None
        if not pod.spec.node_name:
            self.out.write("error: pod is not scheduled yet\n")
            return None
        try:
            node = self.cs.nodes.get(pod.spec.node_name)
        except NotFoundError:
            self.out.write(f'error: node "{pod.spec.node_name}" not found\n')
            return None
        if not node.status.kubelet_url:
            self.out.write("error: node exposes no kubelet endpoint\n")
            return None
        c = container or (pod.spec.containers[0].name if pod.spec.containers else "")
        return node.status.kubelet_url, c, pod.spec.node_name

    def logs_follow(self, name: str, namespace: Optional[str] = None,
                    container: str = "", timeout: float = 10.0,
                    poll: float = 0.2, tail: int = 0) -> int:
        """``kubectl logs -f [--tail N]``: the last N existing lines (all
        when N=0), then new lines as they appear (the reference streams
        the kubelet's follow; a bounded poll here so scripts terminate)."""
        import time as _time

        seen = 0
        first = True
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            buf = io.StringIO()
            sub = Kubectl(self.cs, out=buf)
            if sub.logs(name, namespace, container) != 0:
                self.out.write(buf.getvalue())
                return 1
            lines = buf.getvalue().splitlines()
            if first and tail:
                # --tail bounds the backlog; everything AFTER it follows
                start = max(0, len(lines) - tail)
            else:
                start = seen
            for line in lines[start:]:
                self.out.write(line + "\n")
            seen = len(lines)
            first = False
            _time.sleep(poll)
        return 0

    def logs(self, name: str, namespace: Optional[str] = None,
             container: str = "", tail: int = 0) -> int:
        """``kubectl logs`` via the pod/log subresource (apiserver proxies
        to the owning node's kubelet read API)."""
        import urllib.error
        import urllib.request

        ns = namespace or "default"
        base = getattr(self.cs.store, "base_url", None)
        try:
            if base is None:
                # in-proc clientset: reach the kubelet URL directly
                resolved = self._kubelet_target(name, ns, container)
                if resolved is None:
                    return 1
                kubelet_url, c, _ = resolved
                url = f"{kubelet_url}/containerLogs/{ns}/{name}/{c}"
                if tail:
                    url += f"?tailLines={tail}"
                with urllib.request.urlopen(url, timeout=10) as r:
                    self.out.write(r.read().decode())
            else:
                path = f"/api/v1/namespaces/{ns}/pods/{name}/log"
                sep = "?"
                if container:
                    path += f"{sep}container={container}"
                    sep = "&"
                if tail:
                    path += f"{sep}tailLines={tail}"
                # through the store: same credential AND same TLS context
                self.out.write(self.cs.store.raw("GET", path).decode())
            return 0
        except urllib.error.HTTPError as e:
            self.out.write(f"error: {e.read().decode()}\n")
            return 1
        except Exception as e:
            self.out.write(f"error: {e}\n")
            return 1

    def exec(self, name: str, command: list[str], namespace: Optional[str] = None,
             container: str = "") -> int:
        """``kubectl exec POD -- cmd...`` via the pods/exec subresource."""
        import json as _json
        import urllib.error
        import urllib.request

        ns = namespace or "default"
        base = getattr(self.cs.store, "base_url", None)
        try:
            if base is None:
                resolved = self._kubelet_target(name, ns, container)
                if resolved is None:
                    return 1
                kubelet_url, c, exec_node = resolved
                # direct kubelet path: mint the cluster-key exec credential
                from ..auth.authn import kubelet_exec_token

                req = urllib.request.Request(
                    f"{kubelet_url}/exec/{ns}/{name}/{c}",
                    data=_json.dumps({"command": command}).encode(),
                    headers={"Content-Type": "application/json",
                             "Authorization": f"Bearer {kubelet_exec_token(exec_node)}"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=30) as r:
                    out = _json.loads(r.read())
            else:
                path = f"/api/v1/namespaces/{ns}/pods/{name}/exec"
                if container:
                    path += f"?container={container}"
                out = _json.loads(self.cs.store.raw(
                    "POST", path, body={"command": command}, timeout=30))
        except urllib.error.HTTPError as e:
            self.out.write(f"error: {e.read().decode()}\n")
            return 1
        except Exception as e:
            self.out.write(f"error: {e}\n")
            return 1
        if out.get("stdout"):
            self.out.write(out["stdout"] + ("\n" if not out["stdout"].endswith("\n") else ""))
        return int(out.get("exitCode", 0))

    # -- scale / cordon / drain -------------------------------------------
    def scale(self, resource: str, name: str, replicas: int, namespace: Optional[str] = None) -> int:
        resource, kind = _resolve(resource)
        # the reference scaler set (kubectl/scale.go): Deployment, RS,
        # RC, StatefulSet (Job scales by parallelism, not supported here)
        if kind not in ("Deployment", "ReplicaSet", "ReplicationController",
                        "StatefulSet"):
            self.out.write(f"error: cannot scale {resource}\n")
            return 1

        def _scale(obj):
            obj.replicas = replicas
            return obj

        try:
            self.cs.client_for(kind).guaranteed_update(name, _scale, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} scaled to {replicas}\n")
        return 0

    def cordon(self, name: str, on: bool = True) -> int:
        def _set(node):
            node.spec.unschedulable = on
            return node

        try:
            self.cs.nodes.guaranteed_update(name, _set, "")
        except (NotFoundError, KeyError):
            self.out.write(f'Error: node "{name}" not found\n')
            return 1
        self.out.write(f"node/{name} {'cordoned' if on else 'uncordoned'}\n")
        return 0

    def drain(self, name: str, ignore_daemonsets: bool = False,
              force: bool = False) -> int:
        """cordon + evict every pod on the node (cmd/drain.go), with the
        reference's safety rails: DaemonSet pods are skipped only with
        --ignore-daemonsets (the DS controller would just recreate them),
        and UNMANAGED pods (no controller owner) abort the drain unless
        --force — they would not come back anywhere else."""
        pods, _ = self.cs.pods.list()
        mine = [p for p in pods if p.spec.node_name == name]
        ds_pods = [p for p in mine
                   if (ref := p.meta.controller_ref()) is not None
                   and ref.kind == "DaemonSet"]
        unmanaged = [p for p in mine if p.meta.controller_ref() is None]
        if ds_pods and not ignore_daemonsets:
            names = ", ".join(p.meta.name for p in ds_pods[:5])
            self.out.write(f"error: cannot delete DaemonSet-managed pods "
                           f"({names}); use --ignore-daemonsets\n")
            return 1
        if unmanaged and not force:
            names = ", ".join(p.meta.name for p in unmanaged[:5])
            self.out.write(f"error: cannot delete pods not managed by a "
                           f"controller ({names}); use --force\n")
            return 1
        rc = self.cordon(name, True)
        if rc:
            return rc
        skip = {p.meta.key for p in ds_pods}
        for pod in mine:
            if pod.meta.key in skip:
                self.out.write(f"pod/{pod.meta.name} ignored (DaemonSet-managed)\n")
                continue
            try:
                self.cs.pods.delete(pod.meta.name, pod.meta.namespace)
                self.out.write(f"pod/{pod.meta.name} evicted\n")
            except NotFoundError:
                pass
        self.out.write(f"node/{name} drained\n")
        return 0

    def top_nodes(self) -> int:
        nodes, _ = self.cs.nodes.list()
        pods, _ = self.cs.pods.list()
        from ..scheduler.units import CPU_MILLI, MEM_MIB, pod_request_vec

        usage: dict[str, list[int]] = {}
        for p in pods:
            if p.spec.node_name:
                vec = pod_request_vec(p)
                u = usage.setdefault(p.spec.node_name, [0, 0])
                u[0] += vec[CPU_MILLI]
                u[1] += vec[MEM_MIB]
        rows = [("NAME", "CPU(requested)", "MEMORY(requested)")]
        for n in nodes:
            u = usage.get(n.meta.name, [0, 0])
            rows.append((n.meta.name, f"{u[0]}m", f"{u[1]}Mi"))
        self._print(*rows)
        return 0

    # -- label / annotate (cmd/label.go, cmd/annotate.go) ------------------
    def _set_map(self, resource: str, name: Optional[str], pairs: list[str],
                 which: str, namespace: Optional[str], overwrite: bool,
                 resource_version: str = "", selector: str = "",
                 all_resources: bool = False) -> int:
        """Shared engine for label/annotate: "k=v" sets, "k-" removes.
        Reference semantics (``label.go:99 RunLabel`` /
        ``annotate.go:180 RunAnnotate``): setting an existing key without
        --overwrite is an error; removing an absent key prints
        ``label %q not found.`` but succeeds; the same key may not be
        both set and removed; --resource-version makes the update
        conditional on the object being at exactly that version (and is
        only valid against a single resource); --all / -l select every
        matching object of the type."""
        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return 1
        sets, removes = {}, []
        for p in pairs:
            if p.endswith("-") and "=" not in p:
                removes.append(p[:-1])
            elif "=" in p:
                k, v = p.split("=", 1)
                if which == "labels" and not _valid_label_value(v):
                    self.out.write(f"error: invalid label value: {p!r}\n")
                    return 1
                sets[k] = v
            else:
                self.out.write(f"error: expected KEY=VALUE or KEY-, got {p!r}\n")
                return 1
        both = [k for k in removes if k in sets]
        if both:
            noun = "a label" if which == "labels" else "an annotation"
            self.out.write(f"error: can not both modify and remove {noun} "
                           f"in the same command\n")
            return 1
        if not sets and not removes:
            self.out.write(f"error: at least one {which[:-1]} update is required\n")
            return 1

        client = self.cs.client_for(kind)
        if all_resources or selector:
            if name:
                # the reference rejects a name combined with --all/-l
                # rather than silently fanning out past it
                self.out.write("error: a resource name may not be specified "
                               "together with --all or a selector\n")
                return 1
            if resource_version:
                self.out.write("error: --resource-version may only be used "
                               "with a single resource\n")
                return 1
            want = None
            if selector:
                want = _parse_selector(selector)
                if want is None:
                    self.out.write(f"error: bad selector {selector!r}\n")
                    return 1
            ns_scope = namespace if namespace is not None else client.default_namespace
            objs, _ = client.list(ns_scope)
            if want is not None:
                objs = [o for o in objs if _labels_match(o, want)]
            names = [o.meta.name for o in objs]
        elif name:
            names = [name]
        else:
            self.out.write("error: one or more resources must be specified "
                           "as <resource> <name> or <resource>/<name>\n")
            return 1

        verbed = "labeled" if which == "labels" else "annotated"
        failed = 0  # the reference visitor continues over the remaining
        # objects on a per-object error and aggregates — bulk runs must
        # not stop half-written
        for nm in names:
            err = []
            absent: set = set()  # collected here: guaranteed_update may
            # retry _mutate on a CAS conflict, and the message must not
            # print once per attempt

            def _mutate(obj):
                if resource_version and \
                        str(obj.meta.resource_version) != str(resource_version):
                    err.append(("conflict", obj.meta.resource_version))
                    raise _AbortMutation
                m = getattr(obj.meta, which)
                if not overwrite:
                    clobbered = [k for k, v in sets.items()
                                 if k in m and m[k] != v]
                    if clobbered:
                        err.append(("overwrite", clobbered[0]))
                        raise _AbortMutation
                absent.clear()
                absent.update(k for k in removes if k not in m)
                m.update(sets)
                for k in removes:
                    m.pop(k, None)
                return obj

            try:
                wrote = _update_if_changed(client, nm, _mutate, namespace)
                for k in sorted(absent):
                    self.out.write(f"{which[:-1]} \"{k}\" not found.\n")
            except _AbortMutation:
                why, detail = err[0]
                if why == "conflict":
                    self.out.write(
                        f"Error from server (Conflict): {resource} \"{nm}\" "
                        f"has been modified (resource version {detail}, "
                        f"requested {resource_version})\n")
                else:
                    self.out.write(
                        f"error: {resource} \"{nm}\": {detail!r} already has "
                        f"a value; use --overwrite\n")
                failed += 1
                continue
            except (NotFoundError, KeyError):
                self.out.write(f'Error: {resource} "{nm}" not found\n')
                failed += 1
                continue
            self.out.write(f"{resource}/{nm} "
                           f"{verbed if wrote else 'not ' + verbed}\n")
        return 1 if failed else 0

    def label(self, resource: str, name: Optional[str], pairs: list[str],
              namespace: Optional[str] = None, overwrite: bool = False,
              resource_version: str = "", selector: str = "",
              all_resources: bool = False) -> int:
        return self._set_map(resource, name, pairs, "labels", namespace,
                             overwrite, resource_version, selector,
                             all_resources)

    def annotate(self, resource: str, name: Optional[str], pairs: list[str],
                 namespace: Optional[str] = None, overwrite: bool = False,
                 resource_version: str = "", selector: str = "",
                 all_resources: bool = False) -> int:
        return self._set_map(resource, name, pairs, "annotations", namespace,
                             overwrite, resource_version, selector,
                             all_resources)

    # -- patch (cmd/patch.go) ----------------------------------------------
    def patch(self, resource: str, name: str, patch: str,
              namespace: Optional[str] = None, patch_type: str = "merge") -> int:
        """``kubectl patch``: merge (RFC 7386 recursive merge, null
        deletes) or json (RFC 6902 add/replace/remove) against the
        object's wire form, re-decoded through the type registry."""
        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return 1
        try:
            doc = json.loads(patch)
        except json.JSONDecodeError as e:
            self.out.write(f"error: bad patch: {e}\n")
            return 1

        errors = []

        from ..api.patch import apply_patch

        def _mutate(obj):
            wire = obj.to_dict()
            try:
                patched = apply_patch(wire, doc, patch_type)
            except (KeyError, IndexError, ValueError, TypeError) as e:
                errors.append(str(e))
                raise _AbortMutation from e
            new = type(obj).from_dict(patched)
            new.meta.uid = obj.meta.uid  # identity is cluster-owned
            new.meta.resource_version = obj.meta.resource_version
            return new

        try:
            wrote = _update_if_changed(self.cs.client_for(kind), name, _mutate, namespace)
        except _AbortMutation:
            self.out.write(f"error: cannot apply patch: {errors[0]}\n")
            return 1
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} patched"
                       f"{'' if wrote else ' (no change)'}\n")
        return 0

    # -- taint (cmd/taint.go) ----------------------------------------------
    def taint(self, name: str, specs: list[str]) -> int:
        """``kubectl taint nodes NAME key=value:Effect`` / ``key:Effect-``
        (removal).  Same key+effect replaces (with the reference's
        "overwrite" message)."""
        adds, removes = [], []
        for spec in specs:
            if spec.endswith("-"):
                body = spec[:-1]
                kv, _, effect = body.partition(":")
                key = kv.split("=", 1)[0]
                removes.append((key, effect))
                continue
            body, _, effect = spec.partition(":")
            if not effect:
                self.out.write(f"error: taint {spec!r} must specify an effect\n")
                return 1
            key, _, value = body.partition("=")
            adds.append(api.Taint(key=key, value=value, effect=effect))
        msgs = []
        missing = []

        def _mutate(node):
            msgs.clear()
            missing.clear()
            taints = list(node.spec.taints)
            for t in adds:
                before = len(taints)
                taints = [x for x in taints if not (x.key == t.key and x.effect == t.effect)]
                msgs.append("modified" if len(taints) != before else "tainted")
                taints.append(t)
            for key, effect in removes:
                kept = [x for x in taints
                        if not (x.key == key and (not effect or x.effect == effect))]
                if len(kept) == len(taints):
                    missing.append(f"{key}:{effect}" if effect else key)
                else:
                    msgs.append("untainted")
                taints = kept
            if missing:
                raise _AbortMutation
            node.spec.taints = taints
            return node

        try:
            wrote = _update_if_changed(self.cs.nodes, name, _mutate, "")
        except _AbortMutation:
            self.out.write(f"error: taint {missing[0]!r} not found\n")
            return 1
        except (NotFoundError, KeyError):
            self.out.write(f'Error: node "{name}" not found\n')
            return 1
        self.out.write(f"node/{name} {msgs[-1] if wrote and msgs else 'unchanged'}\n")
        return 0

    # -- expose / run / autoscale (imperative generators) ------------------
    def expose(self, resource: str, name: str, port: int, target_port: int = 0,
               svc_type: str = "ClusterIP", svc_name: str = "",
               namespace: Optional[str] = None) -> int:
        """``kubectl expose``: generate a Service selecting the workload's
        pods (reference ``cmd/expose.go`` + service generators)."""
        resource, kind = _resolve(resource)
        if kind not in ("Deployment", "ReplicaSet", "Service", "Pod"):
            self.out.write(f"error: cannot expose {resource}\n")
            return 1
        try:
            obj = self.cs.client_for(kind).get(name, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        if kind == "Deployment" or kind == "ReplicaSet":
            selector = dict(obj.selector.match_labels)
        elif kind == "Service":
            selector = dict(obj.selector)
        else:  # Pod
            selector = dict(obj.meta.labels)
        if not selector:
            self.out.write("error: couldn't find a selector to expose\n")
            return 1
        svc = api.Service(
            meta=api.ObjectMeta(name=svc_name or name,
                                namespace=namespace or obj.meta.namespace or "default"),
            selector=selector,
            ports=[api.ServicePort(port=port, target_port=target_port or port)],
            type=svc_type,
        )
        try:
            self.cs.services.create(svc)
        except AlreadyExistsError:
            self.out.write(f'Error: service "{svc.meta.name}" already exists\n')
            return 1
        self.out.write(f"service/{svc.meta.name} exposed\n")
        return 0

    def run(self, name: str, image: str, replicas: int = 1, restart: str = "Always",
            namespace: Optional[str] = None, labels: Optional[str] = None) -> int:
        """``kubectl run`` (reference ``cmd/run.go`` generator ladder):
        restart=Always → Deployment, OnFailure → Job, Never → bare Pod."""
        lbls = dict(p.split("=", 1) for p in (labels or "").split(",") if "=" in p)
        lbls.setdefault("run", name)
        ns = namespace or "default"
        container = api.Container(name=name, image=image)
        try:
            if restart == "Always":
                dep = api.Deployment(
                    meta=api.ObjectMeta(name=name, namespace=ns, labels=dict(lbls)),
                    replicas=replicas,
                    selector=api.LabelSelector.from_match_labels(dict(lbls)),
                    template=api.PodTemplateSpec(
                        labels=dict(lbls), spec=api.PodSpec(containers=[container])),
                )
                self.cs.deployments.create(dep)
                self.out.write(f"deployment/{name} created\n")
            elif restart == "OnFailure":
                from ..api.apps import Job

                job = Job(
                    meta=api.ObjectMeta(name=name, namespace=ns, labels=dict(lbls)),
                    selector=api.LabelSelector.from_match_labels(dict(lbls)),
                    template=api.PodTemplateSpec(
                        labels=dict(lbls),
                        spec=api.PodSpec(containers=[container], restart_policy="OnFailure")),
                )
                self.cs.client_for("Job").create(job)
                self.out.write(f"job/{name} created\n")
            elif restart == "Never":
                pod = api.Pod(
                    meta=api.ObjectMeta(name=name, namespace=ns, labels=dict(lbls)),
                    spec=api.PodSpec(containers=[container], restart_policy="Never"),
                )
                self.cs.pods.create(pod)
                self.out.write(f"pod/{name} created\n")
            else:
                self.out.write(f"error: invalid --restart {restart!r}\n")
                return 1
        except AlreadyExistsError:
            self.out.write(f'Error: "{name}" already exists\n')
            return 1
        return 0

    def autoscale(self, resource: str, name: str, min_replicas: int, max_replicas: int,
                  cpu_percent: int = 80, namespace: Optional[str] = None) -> int:
        """``kubectl autoscale``: generate an HPA targeting the workload."""
        resource, kind = _resolve(resource)
        if kind not in ("Deployment", "ReplicaSet"):
            self.out.write(f"error: cannot autoscale {resource}\n")
            return 1
        try:
            obj = self.cs.client_for(kind).get(name, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        from ..api.cluster import HorizontalPodAutoscaler

        hpa = HorizontalPodAutoscaler(
            meta=api.ObjectMeta(name=name,
                                namespace=namespace or obj.meta.namespace or "default"),
            target_kind=kind, target_name=name,
            min_replicas=min_replicas, max_replicas=max_replicas,
            target_cpu_utilization=cpu_percent,
        )
        try:
            self.cs.client_for("HorizontalPodAutoscaler").create(hpa)
        except AlreadyExistsError:
            self.out.write(f'Error: hpa "{name}" already exists\n')
            return 1
        self.out.write(f"horizontalpodautoscaler/{name} autoscaled\n")
        return 0

    # -- set image / set resources (cmd/set/) ------------------------------
    def set_image(self, resource: str, name: str, pairs: list[str],
                  namespace: Optional[str] = None) -> int:
        """``kubectl set image deployment/NAME container=image ...`` —
        the rolling-update trigger (template change → new RS hash)."""
        resource, kind = _resolve(resource)
        if kind not in ("Deployment", "ReplicaSet", "DaemonSet", "StatefulSet", "Pod"):
            self.out.write(f"error: cannot set image on {resource}\n")
            return 1
        want = {}
        for p in pairs:
            if "=" not in p:
                self.out.write(f"error: expected CONTAINER=IMAGE, got {p!r}\n")
                return 1
            c, img = p.split("=", 1)
            want[c] = img
        missing = []

        def _mutate(obj):
            missing.clear()
            containers = (obj.spec.containers if kind == "Pod"
                          else obj.template.spec.containers)
            by_name = {c.name: c for c in containers}
            for c, img in want.items():
                if c == "*":
                    for cont in containers:
                        cont.image = img
                elif c in by_name:
                    by_name[c].image = img
                else:
                    missing.append(c)
            if missing:
                raise _AbortMutation
            return obj

        try:
            _update_if_changed(self.cs.client_for(kind), name, _mutate, namespace)
        except _AbortMutation:
            self.out.write(f"error: unable to find container {missing[0]!r}\n")
            return 1
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} image updated\n")
        return 0

    def set_resources(self, resource: str, name: str, requests: str = "",
                      limits: str = "", namespace: Optional[str] = None) -> int:
        """``kubectl set resources`` — update every container's
        requests/limits from "cpu=100m,memory=128Mi" strings."""
        from ..api.quantity import Quantity

        resource, kind = _resolve(resource)
        if kind not in ("Deployment", "ReplicaSet", "DaemonSet", "StatefulSet"):
            self.out.write(f"error: cannot set resources on {resource}\n")
            return 1

        def _parse(s: str) -> dict:
            return {k: Quantity(v)
                    for k, v in (p.split("=", 1) for p in s.split(",") if "=" in p)}

        try:
            req, lim = _parse(requests), _parse(limits)
        except ValueError as e:
            self.out.write(f"error: {e}\n")
            return 1

        def _mutate(obj):
            for c in obj.template.spec.containers:
                c.resources.requests.update(req)
                c.resources.limits.update(lim)
            return obj

        try:
            _update_if_changed(self.cs.client_for(kind), name, _mutate, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} resource requirements updated\n")
        return 0

    def set_env(self, resource: str, name: str, pairs: list[str],
                namespace: Optional[str] = None) -> int:
        """``kubectl set env`` — KEY=VALUE sets / KEY- removes on every
        container of the workload's template (cmd/set/set_env.go)."""
        resource, kind = _resolve(resource)
        if kind not in ("Deployment", "ReplicaSet", "DaemonSet", "StatefulSet"):
            self.out.write(f"error: cannot set env on {resource}\n")
            return 1
        sets, removes = {}, []
        for p in pairs:
            if p.endswith("-") and "=" not in p:
                removes.append(p[:-1])
            elif "=" in p:
                k2, _, v = p.partition("=")
                sets[k2] = v
            else:
                self.out.write(f"error: expected KEY=VALUE or KEY-, got {p!r}\n")
                return 1

        def _mutate(obj):
            for c in obj.template.spec.containers:
                c.env.update(sets)
                for k2 in removes:
                    c.env.pop(k2, None)
            return obj

        try:
            _update_if_changed(self.cs.client_for(kind), name, _mutate, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} env updated\n")
        return 0

    def set_selector(self, resource: str, name: str, selector: str,
                     namespace: Optional[str] = None) -> int:
        """``kubectl set selector`` (cmd/set/set_selector.go): rewrite a
        Service's selector (equality map) or a workload's label
        selector."""
        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return 1
        # equality-only, like the reference ("selector must be
        # equality-based"): k=v[,k=v...]
        pairs: Optional[dict] = {}
        for part in [s.strip() for s in selector.split(",") if s.strip()]:
            k2, eq, v = part.partition("=")
            if not eq or not k2 or "!" in k2 or "=" in v:
                pairs = None
                break
            pairs[k2] = v
        if not pairs:
            self.out.write(f"error: bad selector {selector!r} "
                           f"(key=value[,key=value...])\n")
            return 1
        from ..api.selectors import LabelSelector

        def _mutate(obj):
            if kind == "Service":
                obj.selector = dict(pairs)
            elif hasattr(obj, "selector"):
                obj.selector = LabelSelector.from_match_labels(pairs)
            else:
                raise KeyError(kind)
            return obj

        try:
            _update_if_changed(self.cs.client_for(kind), name, _mutate, namespace)
        except NotFoundError:
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        except KeyError:
            self.out.write(f"error: cannot set selector on {resource}/{name}\n")
            return 1
        self.out.write(f"{resource}/{name} selector updated\n")
        return 0

    def set_subject(self, resource: str, name: str, users: list[str],
                    groups: list[str], serviceaccounts: list[str],
                    namespace: Optional[str] = None) -> int:
        """``kubectl set subject`` (cmd/set/set_subject.go): append
        users/groups/serviceaccounts to a (Cluster)RoleBinding's subject
        list, deduplicated (within the flags AND against the binding)."""
        resource, kind = _resolve(resource)
        if kind not in ("RoleBinding", "ClusterRoleBinding"):
            self.out.write(f"error: cannot set subject on {resource}\n")
            return 1
        want, err = _build_subjects(users, groups, serviceaccounts)
        if err is not None:
            self.out.write(err)
            return 1

        def _mutate(obj):
            have = {(s.kind, s.name, s.namespace) for s in obj.subjects}
            for s in want:
                if (s.kind, s.name, s.namespace) not in have:
                    have.add((s.kind, s.name, s.namespace))
                    obj.subjects.append(s)
            return obj

        try:
            _update_if_changed(self.cs.client_for(kind), name, _mutate, namespace)
        except NotFoundError:
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} subjects updated\n")
        return 0

    def set_serviceaccount(self, resource: str, name: str, sa_name: str,
                           namespace: Optional[str] = None) -> int:
        """``kubectl set serviceaccount`` (cmd/set/set_serviceaccount.go):
        point the workload template's serviceAccountName at ``sa_name``."""
        resource, kind = _resolve(resource)
        if kind not in ("Deployment", "ReplicaSet", "DaemonSet", "StatefulSet"):
            self.out.write(f"error: cannot set serviceaccount on {resource}\n")
            return 1

        def _mutate(obj):
            obj.template.spec.service_account_name = sa_name
            return obj

        try:
            _update_if_changed(self.cs.client_for(kind), name, _mutate, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} serviceaccount updated\n")
        return 0

    # -- auth can-i (cmd/auth/cani.go) -------------------------------------
    def auth_can_i(self, verb: str, resource: str, name: str = "",
                   namespace: Optional[str] = None) -> int:
        """POSTs a SelfSubjectAccessReview; the server evaluates its live
        authorizer for the calling identity.  Exit 0 yes / 1 no."""
        plural, _ = _resolve(resource)
        if getattr(self.cs.store, "base_url", None) is None:
            # in-proc clientset bypasses the filter chain entirely: every
            # verb IS allowed, so say so rather than guess at policy
            self.out.write("yes\n")
            return 0
        body = {"spec": {"resourceAttributes": {
            "verb": verb, "resource": plural, "name": name,
            "namespace": namespace or "default",
        }}}
        try:
            resp = self.cs.store.raw(
                "POST", "/apis/authorization.k8s.io/v1/selfsubjectaccessreviews",
                body=body)
            status = json.loads(resp).get("status") or {}
        except Exception as e:
            self.out.write(f"error: {e}\n")
            return 1
        self.out.write("yes\n" if status.get("allowed") else "no\n")
        return 0 if status.get("allowed") else 1

    # -- discovery verbs ---------------------------------------------------
    def api_versions(self) -> int:
        versions = ["v1"]
        if getattr(self.cs.store, "base_url", None) is not None:
            try:
                versions = json.loads(self.cs.store.raw("GET", "/api")).get("versions", ["v1"])
                for g in json.loads(self.cs.store.raw("GET", "/apis")).get("groups", []):
                    versions.append(g["name"])
            except Exception as e:
                self.out.write(f"error: could not reach server: {e}\n")
                return 1
        for v in versions:
            self.out.write(v + "\n")
        return 0

    def api_resources(self) -> int:
        """Table of every servable resource, from live discovery (remote)
        or the type registry (in-proc) — CRDs included either way."""
        base = getattr(self.cs.store, "base_url", None)
        rows = [("NAME", "SHORTNAMES", "KIND", "NAMESPACED")]
        short_by_plural: dict[str, list] = {}
        for s, plural in _SHORT_NAMES.items():
            short_by_plural.setdefault(plural, []).append(s)
        if base is not None:
            try:
                resources = json.loads(self.cs.store.raw("GET", "/api/v1")).get("resources", [])
            except Exception as e:
                self.out.write(f"error: could not reach server: {e}\n")
                return 1
        else:
            resources = [
                {"name": plural, "kind": kind,
                 "namespaced": kind not in api.CLUSTER_SCOPED_KINDS}
                for kind, plural in sorted(api.KIND_PLURALS.items())
            ]
        for res in sorted(resources, key=lambda r: r["name"]):
            rows.append((res["name"], ",".join(short_by_plural.get(res["name"], [])),
                         res["kind"], res["namespaced"]))
        self._print(*rows)
        return 0

    def version(self) -> int:
        from .. import __version__

        self.out.write(f"Client Version: {__version__}\n")
        if getattr(self.cs.store, "base_url", None) is not None:
            try:
                data = json.loads(self.cs.store.raw("GET", "/version"))
                self.out.write(f"Server Version: {data['version']}\n")
            except Exception as e:
                self.out.write(f"error: could not reach server: {e}\n")
                return 1
        return 0

    def cluster_info(self) -> int:
        base = getattr(self.cs.store, "base_url", None)
        if base is None:
            self.out.write("Kubernetes master is running in-process\n")
            return 0
        try:
            ok = json.loads(self.cs.store.raw("GET", "/healthz")).get("status") == "ok"
        except Exception:
            ok = False
        self.out.write(f"Kubernetes master is running at {base} "
                       f"({'healthy' if ok else 'UNREACHABLE'})\n")
        return 0 if ok else 1

    # -- attach / cp / port-forward / proxy (streaming verbs) --------------
    def attach(self, name: str, namespace: Optional[str] = None,
               container: str = "") -> int:
        """``kubectl attach POD``: the container's output stream (no TTY
        at this depth — reference attach without stdin)."""
        import urllib.error
        import urllib.request

        ns = namespace or "default"
        base = getattr(self.cs.store, "base_url", None)
        try:
            if base is None:
                resolved = self._kubelet_target(name, ns, container)
                if resolved is None:
                    return 1
                kubelet_url, c, _ = resolved
                with urllib.request.urlopen(
                        f"{kubelet_url}/attach/{ns}/{name}/{c}", timeout=10) as r:
                    self.out.write(r.read().decode())
            else:
                path = f"/api/v1/namespaces/{ns}/pods/{name}/attach"
                if container:
                    path += f"?container={container}"
                self.out.write(self.cs.store.raw("GET", path).decode())
            return 0
        except urllib.error.HTTPError as e:
            self.out.write(f"error: {e.read().decode()}\n")
            return 1
        except Exception as e:
            self.out.write(f"error: {e}\n")
            return 1

    def cp(self, src: str, dst: str, namespace: Optional[str] = None,
           container: str = "") -> int:
        """``kubectl cp`` — ``local pod:/path`` or ``pod:/path local``
        (reference cmd/cp.go: tar over exec; here the pods/cp
        subresource)."""
        import urllib.error
        import urllib.parse as _up
        import urllib.request

        ns = namespace or "default"

        def remote_parts(spec: str):
            if ":" not in spec:
                return None
            pod, _, path = spec.partition(":")
            return pod, path

        src_r, dst_r = remote_parts(src), remote_parts(dst)
        if (src_r is None) == (dst_r is None):
            self.out.write("error: exactly one of SRC/DST must be POD:PATH\n")
            return 1
        pod, path = src_r or dst_r
        base = getattr(self.cs.store, "base_url", None)
        try:
            if base is None:
                resolved = self._kubelet_target(pod, ns, container)
                if resolved is None:
                    return 1
                kubelet_url, c, node = resolved
                from ..auth.authn import kubelet_exec_token

                target = (f"{kubelet_url}/cp/{ns}/{pod}/{c}"
                          f"?path={_up.quote(path)}")
                auth = {"Authorization": f"Bearer {kubelet_exec_token(node)}"}
                if src_r is not None:  # pod -> local
                    req = urllib.request.Request(target, headers=auth)
                    with urllib.request.urlopen(req, timeout=30) as r:
                        data = r.read()
                    open(dst, "wb").write(data)
                else:  # local -> pod
                    req = urllib.request.Request(
                        target, data=open(src, "rb").read(), method="PUT",
                        headers=auth)
                    urllib.request.urlopen(req, timeout=30).read()
            else:
                sub = (f"/api/v1/namespaces/{ns}/pods/{pod}/cp"
                       f"?path={_up.quote(path)}")
                if container:
                    sub += f"&container={container}"
                if src_r is not None:
                    open(dst, "wb").write(self.cs.store.raw("GET", sub))
                else:
                    # raw() sends dict bodies; file bytes need a manual PUT
                    req = urllib.request.Request(
                        f"{base}{sub}", data=open(src, "rb").read(), method="PUT")
                    token = getattr(self.cs.store, "token", None)
                    if token:
                        req.add_header("Authorization", f"Bearer {token}")
                    urllib.request.urlopen(
                        req, timeout=30,
                        context=getattr(self.cs.store, "_ssl_ctx", None)).read()
            self.out.write("copied\n")
            return 0
        except FileNotFoundError as e:
            self.out.write(f"error: {e}\n")
            return 1
        except urllib.error.HTTPError as e:
            self.out.write(f"error: {e.read().decode()}\n")
            return 1
        except Exception as e:
            self.out.write(f"error: {e}\n")
            return 1

    def port_forward(self, name: str, ports: str,
                     namespace: Optional[str] = None):
        """``kubectl port-forward POD LOCAL:REMOTE`` — a real local
        listener forwarding each connection to the pod's IP (the
        reference forwards SPDY streams via the kubelet; the pod IP is
        the hollow fleet's reachable address).  Returns the forwarder
        (caller stops it); None after printing an error."""
        ns = namespace or "default"
        try:
            pod = self.cs.pods.get(name, ns)
        except NotFoundError:
            self.out.write(f'Error: pod "{name}" not found\n')
            return None
        if not pod.status.pod_ip:
            self.out.write("error: pod has no IP\n")
            return None
        local_s, _, remote_s = ports.partition(":")
        try:
            remote = int(remote_s or local_s)
            local = int(local_s) if local_s else 0
        except ValueError:
            self.out.write(f"error: invalid port spec {ports!r} "
                           "(want LOCAL:REMOTE or PORT)\n")
            return None
        from ..proxy.userspace import UserspaceProxier

        fwd = UserspaceProxier()
        try:
            port = fwd.set_service(f"port-forward/{ns}/{name}",
                                   [(pod.status.pod_ip, remote)],
                                   local_port=local)
        except OSError as e:
            self.out.write(f"error: cannot bind local port {local_s}: {e}\n")
            return None
        self.out.write(f"Forwarding from 127.0.0.1:{port} -> {remote}\n")
        fwd.local_port = port
        return fwd

    def proxy(self, port: int = 0):
        """``kubectl proxy``: local HTTP front door that forwards every
        request to the apiserver with this client's credential attached
        (reference cmd/proxy.go).  Returns the running server."""
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        store = self.cs.store
        outer_out = self.out

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _forward(self, method):
                import urllib.error

                try:
                    # bodies forward as RAW bytes: the proxy must not
                    # assume JSON (cp PUTs file payloads through here)
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length) if length else None
                    data = store.raw(method, self.path, body=body)
                    code = 200
                except urllib.error.HTTPError as e:
                    data, code = e.read(), e.code
                except Exception as e:  # noqa: BLE001
                    data, code = str(e).encode(), 502
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._forward("GET")

            def do_POST(self):
                self._forward("POST")

            def do_PUT(self):
                self._forward("PUT")

            def do_DELETE(self):
                self._forward("DELETE")

        if getattr(store, "base_url", None) is None:
            outer_out.write("error: proxy requires --server\n")
            return None
        httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        outer_out.write(f"Starting to serve on 127.0.0.1:{httpd.server_port}\n")
        httpd.local_port = httpd.server_port
        return httpd

    # -- explain / edit (cmd/explain.go, cmd/edit.go) ----------------------
    # -- create generators (cmd/create_*.go) -------------------------------
    def create_resource(self, what: str, name: str, namespace: Optional[str],
                        from_literal: list[str], from_file: list[str],
                        hard: str, tcp: list[str], secret_type: str,
                        svc_type: str = "ClusterIP", verbs: str = "",
                        resources: str = "", role: str = "",
                        clusterrole: str = "", users: list[str] = (),
                        groups: list[str] = (), serviceaccounts: list[str] = (),
                        selector: str = "", min_available: int = 0,
                        image: str = "", replicas: int = 1) -> int:
        """Imperative object generators: ``kubectl create
        namespace|configmap|secret|serviceaccount|quota|service|role|
        rolebinding|clusterrole|clusterrolebinding|pdb|deployment NAME
        ...`` (reference ``cmd/create_*.go``)."""
        import base64

        from ..admission.framework import AdmissionDenied
        from ..api import (
            ConfigMap,
            Namespace,
            ResourceQuota,
            Secret,
            ServiceAccount,
        )
        from ..api.cluster import PodDisruptionBudget
        from ..api.rbac import (
            ClusterRole,
            ClusterRoleBinding,
            PolicyRule,
            Role,
            RoleBinding,
        )
        from ..client.remote import ForbiddenError

        def _kv_data(binary_ok: bool) -> Optional[dict]:
            """key→value from --from-literal/--from-file.  Files read as
            bytes; non-UTF-8 content is allowed only where the target
            kind can hold it (secrets — the canonical home of certs and
            keystores), mirroring the reference's data/binaryData split."""
            data = {}
            for spec in from_literal:
                if "=" not in spec:
                    self.out.write(f"error: --from-literal needs key=value, "
                                   f"got {spec!r}\n")
                    return None
                k, _, v = spec.partition("=")
                data[k] = v
            for path in from_file:
                key, _, p = path.partition("=")
                if not p:
                    key, p = None, path
                try:
                    with open(p, "rb") as fh:
                        raw = fh.read()
                except OSError as e:
                    self.out.write(f"error: {e}\n")
                    return None
                import os as _os

                try:
                    content = raw.decode()
                except UnicodeDecodeError:
                    if not binary_ok:
                        self.out.write(
                            f"error: {p} is not UTF-8; binary content is "
                            f"only supported in secrets\n")
                        return None
                    content = raw
                data[key or _os.path.basename(p)] = content
            return data

        if what == "namespace":
            obj = Namespace(meta=api.ObjectMeta(name=name, namespace=""))
        elif what == "configmap":
            data = _kv_data(binary_ok=False)
            if data is None:
                return 1
            obj = ConfigMap(meta=api.ObjectMeta(name=name), data=data)
        elif what == "secret":
            data = _kv_data(binary_ok=True)
            if data is None:
                return 1
            # the in-repo Secret convention stores plain values (the
            # serviceaccount-token controller does); binary file content
            # is base64-armored so it survives the string field
            obj = Secret(
                meta=api.ObjectMeta(name=name), type=secret_type,
                data={k: (v if isinstance(v, str)
                          else base64.b64encode(v).decode())
                      for k, v in data.items()},
            )
        elif what == "serviceaccount":
            obj = ServiceAccount(meta=api.ObjectMeta(name=name))
        elif what == "quota":
            limits = {}
            for spec in (hard or "").split(","):
                if not spec:
                    continue
                k, _, v = spec.partition("=")
                try:
                    limits[k] = api.Quantity(v)
                except ValueError:
                    self.out.write(f"error: bad quantity {v!r} for {k}\n")
                    return 1
            obj = ResourceQuota(meta=api.ObjectMeta(name=name), hard=limits)
        elif what == "service":
            ports = []
            for spec in tcp or []:
                port_s, _, target_s = spec.partition(":")
                try:
                    port = int(port_s)
                    target = int(target_s) if target_s else port
                except ValueError:
                    self.out.write(f"error: bad --tcp {spec!r}\n")
                    return 1
                ports.append(api.ServicePort(name=f"tcp-{port}", port=port,
                                             target_port=target))
            obj = api.Service(meta=api.ObjectMeta(name=name),
                              selector={"app": name}, ports=ports,
                              type=svc_type)
        elif what in ("role", "clusterrole"):
            if not verbs or not resources:
                self.out.write("error: --verb and --resource are required\n")
                return 1
            rule = PolicyRule(verbs=verbs.split(","),
                              resources=resources.split(","))
            cls = Role if what == "role" else ClusterRole
            obj = cls(meta=api.ObjectMeta(name=name), rules=[rule])
        elif what in ("rolebinding", "clusterrolebinding"):
            if bool(role) == bool(clusterrole):
                self.out.write("error: exactly one of --role/--clusterrole "
                               "is required\n")
                return 1
            subjects, err = _build_subjects(users, groups, serviceaccounts)
            if err is not None:
                self.out.write(err)
                return 1
            cls = RoleBinding if what == "rolebinding" else ClusterRoleBinding
            obj = cls(meta=api.ObjectMeta(name=name), subjects=subjects,
                      role_kind="ClusterRole" if clusterrole else "Role",
                      role_name=clusterrole or role)
        elif what == "poddisruptionbudget":
            want = _parse_selector(selector) if selector else None
            if want is None:
                self.out.write("error: --selector is required (and must "
                               "parse)\n")
                return 1
            obj = PodDisruptionBudget(
                meta=api.ObjectMeta(name=name),
                min_available=min_available,
                selector=want,  # _parse_selector returns a LabelSelector
            )
        elif what == "deployment":
            # cmd/create_deployment.go: app=NAME labels/selector, one
            # container named after the image's basename
            if not image:
                self.out.write("error: --image is required\n")
                return 1
            from ..api.selectors import LabelSelector

            # basename, digest/tag stripped ("nginx@sha256:..." -> nginx)
            cname = image.split("/")[-1].split("@")[0].split(":")[0] or name
            obj = api.Deployment(
                meta=api.ObjectMeta(name=name, labels={"app": name}),
                replicas=replicas,
                selector=LabelSelector.from_match_labels({"app": name}),
                template=api.PodTemplateSpec(
                    labels={"app": name},
                    spec=api.PodSpec(containers=[
                        api.Container(name=cname, image=image)]),
                ),
            )
        else:
            self.out.write(f"error: unknown generator {what!r}\n")
            return 1
        if namespace and hasattr(obj.meta, "namespace") and obj.meta.namespace != "":
            obj.meta.namespace = namespace
        kind = type(obj).KIND
        try:
            self.cs.client_for(kind).create(obj)
        except AlreadyExistsError:
            self.out.write(f"Error: {kind} {name!r} already exists\n")
            return 1
        except (AdmissionDenied, ForbiddenError) as e:
            self.out.write(f"Error from server (Forbidden): {e}\n")
            return 1
        self.out.write(f"{KIND_TO_RESOURCE[kind]}/{name} created\n")
        return 0

    # -- certificate approve/deny (cmd/certificates.go) --------------------
    def certificate(self, action: str, name: str) -> int:
        """Flip a CSR's approval condition; the certificates controller
        then issues (reference ``cmd/certificates.go`` +
        ``pkg/controller/certificates``)."""
        cond_type = "Approved" if action == "approve" else "Denied"
        past = "approved" if action == "approve" else "denied"

        def _mutate(csr):
            have = {c.get("type") for c in csr.conditions}
            if cond_type in have:
                return csr  # idempotent: the no-op write is skipped
            if ("Denied" if cond_type == "Approved" else "Approved") in have:
                raise _AbortMutation
            csr.conditions.append({
                "type": cond_type, "reason": "KubectlCertificate",
                "message": f"{past} via kubectl certificate {action}",
            })
            return csr

        try:
            _update_if_changed(self.cs.certificatesigningrequests, name,
                               _mutate, None)
        except _AbortMutation:
            self.out.write(f"error: CSR {name!r} is already "
                           f"{'denied' if cond_type == 'Approved' else 'approved'}\n")
            return 1
        except (NotFoundError, KeyError):
            self.out.write(f'Error: certificatesigningrequest "{name}" not found\n')
            return 1
        self.out.write(f"certificatesigningrequest/{name} {past}\n")
        return 0

    # -- replace (cmd/replace.go) ------------------------------------------
    def replace(self, filename: str, force: bool = False) -> int:
        """Full-object update from a manifest; the object must exist
        (create is ``kubectl create``'s job).  ``--force`` deletes and
        recreates — a new uid, like the reference's delete+create path."""
        from ..admission.framework import AdmissionDenied
        from ..client.remote import ForbiddenError

        rc = 0
        for doc in self._load_manifests(filename):
            kind = doc.get("kind", "")
            if kind not in KIND_TO_RESOURCE:
                self.out.write(f"error: unknown kind {kind!r} in manifest\n")
                rc = 1
                continue
            client = self.cs.client_for(kind)
            desired = api.from_dict(doc)
            name = desired.meta.name
            plural = KIND_TO_RESOURCE[kind]
            if force:
                try:
                    client.delete(name, desired.meta.namespace or None)
                except (NotFoundError, KeyError):
                    pass
                # identity is cluster-owned: recreate mints a fresh uid even
                # if the manifest was exported from a live object
                desired.meta.uid = ""
                desired.meta.resource_version = 0
                desired.meta.creation_revision = 0
                try:
                    client.create(desired)
                except (AdmissionDenied, ForbiddenError, AlreadyExistsError) as e:
                    self.out.write(f"Error from server (Forbidden): {e}\n")
                    rc = 1
                    continue
                self.out.write(f"{plural}/{name} replaced\n")
                continue

            def _swap(live):
                desired.meta.uid = live.meta.uid
                desired.meta.resource_version = live.meta.resource_version
                desired.meta.creation_revision = live.meta.creation_revision
                return desired

            try:
                client.guaranteed_update(name, _swap, desired.meta.namespace or None)
            except (NotFoundError, KeyError):
                self.out.write(f'Error: {plural} "{name}" not found '
                               f'(use create or --force)\n')
                rc = 1
                continue
            except (AdmissionDenied, ForbiddenError) as e:
                self.out.write(f"Error from server (Forbidden): {e}\n")
                rc = 1
                continue
            self.out.write(f"{plural}/{name} replaced\n")
        return rc

    # -- convert (cmd/convert.go) ------------------------------------------
    def convert(self, filename: str, output_version: str) -> int:
        """Re-encode manifests between API versions through the scheme's
        hub-and-spoke converters (``api/scheme.py`` — decode to internal,
        encode to the requested group/version)."""
        from ..api.scheme import convert_from_internal

        docs = []
        for doc in self._load_manifests(filename):  # already internal form
            kind = doc.get("kind", "")
            if kind not in KIND_TO_RESOURCE:
                self.out.write(f"error: unknown kind {kind!r} in manifest\n")
                return 1
            docs.append(convert_from_internal(doc, output_version))
        for i, doc in enumerate(docs):
            if i:
                self.out.write("---\n")
            self.out.write(yaml.safe_dump(doc, sort_keys=False))
        return 0

    # -- completion (cmd/completion.go) ------------------------------------
    def completion(self, shell: str) -> int:
        """Emit a shell completion script over the live verb + resource
        tables (the reference generates from cobra; here from argparse's
        registered subcommands)."""
        verbs = sorted(ALL_VERBS)
        resources = sorted(set(KIND_TO_RESOURCE.values()))
        if shell == "bash":
            self.out.write(
                "# bash completion for kubectl\n"
                "_kubectl_completions() {\n"
                "  local cur=${COMP_WORDS[COMP_CWORD]}\n"
                f"  local verbs=\"{' '.join(verbs)}\"\n"
                f"  local resources=\"{' '.join(resources)}\"\n"
                "  if [ $COMP_CWORD -eq 1 ]; then\n"
                "    COMPREPLY=($(compgen -W \"$verbs\" -- \"$cur\"))\n"
                "  else\n"
                "    COMPREPLY=($(compgen -W \"$resources\" -- \"$cur\"))\n"
                "  fi\n"
                "}\n"
                "complete -F _kubectl_completions kubectl\n")
            return 0
        if shell == "zsh":
            self.out.write(
                "#compdef kubectl\n"
                f"local -a verbs=({' '.join(verbs)})\n"
                f"local -a resources=({' '.join(resources)})\n"
                "if (( CURRENT == 2 )); then\n"
                "  _describe 'verb' verbs\n"
                "else\n"
                "  _describe 'resource' resources\n"
                "fi\n")
            return 0
        self.out.write(f"error: unsupported shell {shell!r}\n")
        return 1

    # -- config (cmd/config/) ----------------------------------------------
    def config(self, args: list[str], kubeconfig: Optional[str] = None) -> int:
        """kubeconfig file manipulation: view / get-contexts /
        current-context / use-context / set-context / set-cluster /
        delete-context over the reference's clusters+contexts+users shape
        (``staging/src/k8s.io/client-go/tools/clientcmd/api/types.go``)."""
        import os

        path = kubeconfig or os.environ.get("KUBECONFIG") or os.path.expanduser(
            "~/.kube/config")

        def load() -> dict:
            try:
                with open(path) as f:
                    return yaml.safe_load(f) or {}
            except FileNotFoundError:
                return {}

        def save(cfg: dict) -> None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                yaml.safe_dump(cfg, f, sort_keys=False)

        if not args:
            self.out.write("error: config needs a subcommand "
                           "(view|get-contexts|current-context|use-context|"
                           "set-context|set-cluster|delete-context)\n")
            return 1
        sub, rest = args[0], args[1:]
        cfg = load()
        if sub == "view":
            self.out.write(yaml.safe_dump(cfg or {"apiVersion": "v1",
                                                  "kind": "Config"},
                                          sort_keys=False))
            return 0
        if sub == "current-context":
            cur = cfg.get("current-context", "")
            if not cur:
                self.out.write("error: current-context is not set\n")
                return 1
            self.out.write(cur + "\n")
            return 0
        if sub == "get-contexts":
            cur = cfg.get("current-context", "")
            self.out.write("CURRENT   NAME   CLUSTER   USER\n")
            for c in cfg.get("contexts", []):
                mark = "*" if c.get("name") == cur else " "
                ctx = c.get("context", {})
                self.out.write(f"{mark}         {c.get('name')}   "
                               f"{ctx.get('cluster', '')}   "
                               f"{ctx.get('user', '')}\n")
            return 0
        if sub == "use-context":
            if not rest:
                self.out.write("error: use-context needs a name\n")
                return 1
            if not any(c.get("name") == rest[0] for c in cfg.get("contexts", [])):
                self.out.write(f"error: no context exists with the name "
                               f"{rest[0]!r}\n")
                return 1
            cfg["current-context"] = rest[0]
            save(cfg)
            self.out.write(f'Switched to context "{rest[0]}".\n')
            return 0
        if sub == "set-context":
            if not rest:
                self.out.write("error: set-context needs a name\n")
                return 1
            name, kv = rest[0], dict(p.split("=", 1) for p in rest[1:] if "=" in p)
            ctxs = cfg.setdefault("contexts", [])
            for c in ctxs:
                if c.get("name") == name:
                    c.setdefault("context", {}).update(kv)
                    break
            else:
                ctxs.append({"name": name, "context": kv})
            save(cfg)
            self.out.write(f'Context "{name}" modified.\n')
            return 0
        if sub == "set-cluster":
            if not rest:
                self.out.write("error: set-cluster needs a name\n")
                return 1
            name, kv = rest[0], dict(p.split("=", 1) for p in rest[1:] if "=" in p)
            clusters = cfg.setdefault("clusters", [])
            for c in clusters:
                if c.get("name") == name:
                    c.setdefault("cluster", {}).update(kv)
                    break
            else:
                clusters.append({"name": name, "cluster": kv})
            save(cfg)
            self.out.write(f'Cluster "{name}" set.\n')
            return 0
        if sub == "delete-context":
            if not rest:
                self.out.write("error: delete-context needs a name\n")
                return 1
            before = len(cfg.get("contexts", []))
            cfg["contexts"] = [c for c in cfg.get("contexts", [])
                               if c.get("name") != rest[0]]
            if len(cfg["contexts"]) == before:
                self.out.write(f"error: cannot delete context {rest[0]!r}, "
                               f"not in {path}\n")
                return 1
            if cfg.get("current-context") == rest[0]:
                cfg.pop("current-context", None)
            save(cfg)
            self.out.write(f'deleted context {rest[0]} from {path}\n')
            return 0
        self.out.write(f"error: unknown config subcommand {sub!r}\n")
        return 1

    # -- cluster-info dump (cmd/clusterinfo_dump.go) -----------------------
    def cluster_info_dump(self, output_directory: str = "") -> int:
        """Dump cluster state (nodes + per-namespace pods/services/
        events/RCs/RSs/deployments) as JSON — to stdout, or one file per
        kind under --output-directory like the reference."""
        import os

        dumps: list[tuple[str, list]] = [
            ("nodes", self.cs.nodes.list()[0]),
        ]
        for plural in ("pods", "services", "events", "replicationcontrollers",
                       "replicasets", "deployments", "daemonsets"):
            try:
                client = getattr(self.cs, plural)
            except AttributeError:
                continue
            dumps.append((plural, client.list()[0]))  # all namespaces
        if output_directory:
            for plural, objs in dumps:
                p = os.path.join(output_directory, f"{plural}.json")
                os.makedirs(output_directory, exist_ok=True)
                with open(p, "w") as f:
                    json.dump({"kind": "List",
                               "items": [o.to_dict() for o in objs]}, f,
                              indent=2, default=str)
            self.out.write(f"Cluster info dumped to {output_directory}\n")
            return 0
        for plural, objs in dumps:
            self.out.write(json.dumps(
                {"kind": "List", "resource": plural,
                 "items": [o.to_dict() for o in objs]}, indent=2,
                default=str) + "\n")
        return 0

    def explain(self, resource: str) -> int:
        """``kubectl explain RESOURCE[.field...]``: the wire schema of a
        kind, derived from the live type registry (the discovery-driven
        analogue of the reference's OpenAPI-backed explain)."""
        parts = resource.split(".")
        plural, kind = _resolve(parts[0])
        if kind is None:
            self.out.write(f"error: unknown resource {parts[0]!r}\n")
            return 1
        cls = api.KINDS[kind]
        doc = cls().to_dict()
        for seg in parts[1:]:
            if not isinstance(doc, dict) or seg not in doc:
                self.out.write(f"error: field {seg!r} does not exist\n")
                return 1
            doc = doc[seg]
            if isinstance(doc, list):
                doc = doc[0] if doc else {}
        self.out.write(f"KIND:     {kind}\n")
        if cls.__doc__:
            self.out.write(f"DESCRIPTION:\n  {cls.__doc__.strip().splitlines()[0]}\n")
        self.out.write("FIELDS:\n")

        def emit(d, indent):
            if not isinstance(d, dict):
                self.out.write(f"{' ' * indent}<{type(d).__name__}>\n")
                return
            for k, v in sorted(d.items()):
                tname = ("Object" if isinstance(v, dict)
                         else "[]Object" if isinstance(v, list)
                         else type(v).__name__)
                self.out.write(f"{' ' * indent}{k}\t<{tname}>\n")

        emit(doc, 2)
        return 0

    def edit(self, resource: str, name: str, namespace: Optional[str] = None) -> int:
        """``kubectl edit``: object -> $EDITOR -> update (the reference's
        edit loop without the conflict-retry interactive path)."""
        import os
        import subprocess
        import tempfile

        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return 1
        client = self.cs.client_for(kind)
        try:
            obj = client.get(name, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        editor = os.environ.get("EDITOR", "vi")
        with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
            yaml.safe_dump(obj.to_dict(), f, sort_keys=False)
            tmp = f.name
        try:
            rc = subprocess.run([*editor.split(), tmp]).returncode
        except OSError as e:
            os.unlink(tmp)
            self.out.write(f"error: cannot run editor {editor!r}: {e}\n")
            return 1
        if rc != 0:
            os.unlink(tmp)
            self.out.write("Edit cancelled\n")
            return 1
        try:
            edited = yaml.safe_load(open(tmp).read())
        except yaml.YAMLError as e:
            # the user's edits must SURVIVE a typo — keep the file and
            # point at it (the reference re-opens the editor; one shot
            # here, but never data loss)
            self.out.write(f"error: edited file is not valid YAML: {e}\n"
                           f"your changes are preserved in {tmp}\n")
            return 1
        os.unlink(tmp)
        if edited == obj.to_dict():
            self.out.write("Edit cancelled, no changes made\n")
            return 0

        def _replace(cur):
            new = type(cur).from_dict(edited)
            new.meta.uid = cur.meta.uid
            new.meta.resource_version = cur.meta.resource_version
            return new

        client.guaranteed_update(name, _replace, namespace)
        self.out.write(f"{resource}/{name} edited\n")
        return 0

    # -- rolling-update (cmd/rollingupdate.go, rolling_updater.go) ---------
    def rolling_update(self, old_name: str, image: str,
                       namespace: Optional[str] = None,
                       new_name: str = "", drive=None) -> int:
        """Client-side rolling update of a ReplicaSet (the reference's
        kubectl rolling-update on RCs): create the new RS at 0, then step
        new up / old down one replica at a time, finally delete the old.
        ``drive`` (callable) runs controllers between steps so replica
        counts actually converge (tests pass a manager pump; against a
        live cluster the controller manager does it)."""
        ns = namespace or "default"
        try:
            old = self.cs.replicasets.get(old_name, ns)
        except NotFoundError:
            self.out.write(f'Error: replicaset "{old_name}" not found\n')
            return 1
        new_name = new_name or f"{old_name}-next"
        desired = old.replicas
        new_rs = type(old).from_dict(old.to_dict())
        new_rs.meta = api.ObjectMeta(name=new_name, namespace=ns,
                                     labels=dict(old.meta.labels))
        new_rs.replicas = 0
        # distinct selector + template labels so the two RSes never adopt
        # each other's pods (the reference requires a differentiating label)
        bump = {"rolling-update": new_name}
        new_rs.selector = api.LabelSelector.from_match_labels(
            {**old.selector.match_labels, **bump})
        new_rs.template.labels.update(bump)
        if new_rs.template.spec.containers:
            new_rs.template.spec.containers[0].image = image
        try:
            self.cs.replicasets.create(new_rs)
        except AlreadyExistsError:
            self.out.write(f'Error: replicaset "{new_name}" already exists\n')
            return 1
        self.out.write(f"Created {new_name}\n")
        for step in range(1, desired + 1):
            def _scale_new(rs, n=step):
                rs.replicas = n
                return rs

            def _scale_old(rs, n=desired - step):
                rs.replicas = n
                return rs

            self.cs.replicasets.guaranteed_update(new_name, _scale_new, ns)
            self.cs.replicasets.guaranteed_update(old_name, _scale_old, ns)
            self.out.write(f"Scaling {new_name} up to {step}, "
                           f"{old_name} down to {desired - step}\n")
            if drive is not None:
                drive()
        self.cs.replicasets.delete(old_name, ns)
        self.out.write(f"Update succeeded. Deleting {old_name}\n")
        return 0

    # -- wait (cmd/wait.go) ------------------------------------------------
    def wait_for(self, resource: str, name: str, condition: str,
                 namespace: Optional[str] = None, timeout: float = 30.0) -> int:
        """``kubectl wait RES/NAME --for=condition=X|delete`` — polls the
        API (the reference watches; same observable behavior)."""
        import time as _time

        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return 1
        client = self.cs.client_for(kind)
        want_delete = condition == "delete"
        want_cond = condition.split("=", 1)[1] if condition.startswith("condition=") else ""
        if not want_delete and not want_cond:
            self.out.write(f"error: unsupported --for {condition!r}\n")
            return 1
        deadline = _time.monotonic() + timeout
        while True:
            try:
                obj = client.get(name, namespace)
            except (NotFoundError, KeyError):
                if want_delete:
                    self.out.write(f"{resource}/{name} condition met\n")
                    return 0
                obj = None
            if obj is not None and want_cond:
                conds = getattr(getattr(obj, "status", None), "conditions", [])
                for c in conds:
                    if isinstance(c, dict):
                        ctype, cstat = c.get("type", ""), c.get("status", "")
                    else:
                        ctype = getattr(c, "type", "")
                        cstat = getattr(c, "status", "")
                    if ctype == want_cond and cstat == "True":
                        self.out.write(f"{resource}/{name} condition met\n")
                        return 0
            if _time.monotonic() >= deadline:
                self.out.write(f"error: timed out waiting for {condition} on {resource}/{name}\n")
                return 1
            _time.sleep(0.05)


def main(argv: Optional[list[str]] = None, clientset: Optional[Clientset] = None, out=None) -> int:
    """Dispatch wrapper: server-side denials and wire errors become the
    reference's "Error from server" line + exit 1, never a traceback
    (any verb can hit a 403 once the apiserver runs with authorization)."""
    from ..client.remote import ForbiddenError, RemoteError

    try:
        return _main(argv, clientset, out)
    except ForbiddenError as e:
        (out or sys.stdout).write(f"Error from server (Forbidden): {e}\n")
        return 1
    except RemoteError as e:
        (out or sys.stdout).write(f"Error from server: {e}\n")
        return 1


def _main(argv: Optional[list[str]] = None, clientset: Optional[Clientset] = None, out=None) -> int:
    # SUPPRESS so a subparser never clobbers a value parsed before the verb
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--server", default=argparse.SUPPRESS)
    common.add_argument("--token", default=argparse.SUPPRESS)
    common.add_argument("--kubeconfig", default=argparse.SUPPRESS)
    common.add_argument("--certificate-authority", dest="ca_file",
                        default=argparse.SUPPRESS)
    common.add_argument("--client-certificate", dest="client_cert",
                        default=argparse.SUPPRESS)
    common.add_argument("--client-key", dest="client_key",
                        default=argparse.SUPPRESS)
    common.add_argument("-n", "--namespace", default=argparse.SUPPRESS)
    common.add_argument("-o", "--output", default=argparse.SUPPRESS)  # ""|json|yaml|jsonpath=...

    parser = argparse.ArgumentParser(prog="kubectl-tpu", parents=[common])
    sub = parser.add_subparsers(dest="verb", required=True)

    p = sub.add_parser("get", parents=[common])
    p.add_argument("resource")
    p.add_argument("name", nargs="?")
    p.add_argument("-l", "--selector", default="")
    p.add_argument("-w", "--watch", action="store_true")
    p.add_argument("--watch-timeout", type=float, default=30.0)
    p.add_argument("--sort-by", default="")
    p.add_argument("--show-labels", action="store_true")
    p.add_argument("--no-headers", action="store_true")
    p = sub.add_parser("describe", parents=[common])
    p.add_argument("resource")
    p.add_argument("name")
    p = sub.add_parser("create", parents=[common])
    p.add_argument("what", nargs="?",
                   help="generator: namespace|configmap|secret|"
                        "serviceaccount|quota|service (or use -f)")
    p.add_argument("gen_name", nargs="?")
    p.add_argument("gen_extra", nargs="?")
    p.add_argument("-f", "--filename", default=None)
    p.add_argument("--from-literal", action="append", default=[])
    p.add_argument("--from-file", action="append", default=[])
    p.add_argument("--hard", default="")
    p.add_argument("--tcp", action="append", default=[])
    p.add_argument("--type", dest="secret_type", default="Opaque")
    # dest must NOT be "verb": that is the subparser dest, and argparse
    # would clobber the chosen subcommand with the flag's value
    p.add_argument("--verb", dest="rbac_verb", default="",
                   help="role/clusterrole verbs, comma-sep")
    p.add_argument("--resource", dest="rbac_resource", default="",
                   help="role/clusterrole resources, comma-sep")
    p.add_argument("--role", default="")
    p.add_argument("--clusterrole", default="")
    p.add_argument("--user", action="append", default=[])
    p.add_argument("--group", action="append", default=[])
    p.add_argument("--serviceaccount", action="append", default=[],
                   help="ns:name")
    p.add_argument("--min-available", type=int, default=0)
    p.add_argument("-l", "--selector", default=argparse.SUPPRESS)
    p.add_argument("--image", default="",
                   help="container image (create deployment)")
    p.add_argument("--replicas", type=int, default=1)
    p = sub.add_parser("certificate", parents=[common])
    p.add_argument("action", choices=["approve", "deny"])
    p.add_argument("name")
    p = sub.add_parser("apply", parents=[common])
    p.add_argument("subverb", nargs="?", default=None,
                   help="view-last-applied|set-last-applied|"
                        "edit-last-applied (default: declarative apply -f)")
    p.add_argument("target", nargs="?", help="RESOURCE[/NAME]")
    p.add_argument("target_name", nargs="?", help="NAME (two-token form)")
    p.add_argument("-f", "--filename", default=None)
    p.add_argument("--prune", action="store_true")
    p.add_argument("-l", "--selector", default="")
    # -o/--output is inherited from the common parent parser
    p.add_argument("--create-annotation", action="store_true",
                   help="set-last-applied: create the annotation when "
                        "absent instead of erroring")
    p = sub.add_parser("delete", parents=[common])
    p.add_argument("resource")
    p.add_argument("name", nargs="?")
    p.add_argument("-l", "--selector", default="")
    p.add_argument("--cascade", default="background",
                   choices=["background", "orphan"])
    p = sub.add_parser("scale", parents=[common])
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("--replicas", type=int, required=True)
    p = sub.add_parser("cordon", parents=[common])
    p.add_argument("name")
    p = sub.add_parser("uncordon", parents=[common])
    p.add_argument("name")
    p = sub.add_parser("drain", parents=[common])
    p.add_argument("name")
    p.add_argument("--ignore-daemonsets", action="store_true")
    p.add_argument("--force", action="store_true")
    p = sub.add_parser("top", parents=[common])
    p.add_argument("what", choices=["nodes", "pods"])
    p = sub.add_parser("logs", parents=[common])
    p.add_argument("name")
    p.add_argument("-c", "--container", default="")
    p.add_argument("--tail", type=int, default=0)
    p.add_argument("-f", "--follow", action="store_true")
    p.add_argument("--follow-timeout", type=float, default=10.0)
    p = sub.add_parser("exec", parents=[common])
    p.add_argument("name")
    p.add_argument("-c", "--container", default="")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- cmd args...")
    p = sub.add_parser("rollout", parents=[common])
    p.add_argument("action", choices=["status", "history", "undo",
                                      "pause", "resume"])
    p.add_argument("resource")  # "deployment" or "deployment/NAME"
    p.add_argument("name", nargs="?")
    p.add_argument("--to-revision", type=int, default=0)
    for verb in ("label", "annotate"):
        p = sub.add_parser(verb, parents=[common])
        p.add_argument("resource")  # "pods" or "pods/NAME"
        p.add_argument("name", nargs="?")
        p.add_argument("pairs", nargs="*", help="KEY=VALUE or KEY- to remove")
        p.add_argument("--overwrite", action="store_true")
        p.add_argument("--resource-version", dest="resource_version", default="")
        p.add_argument("-l", "--selector", default="")
        p.add_argument("--all", dest="all_resources", action="store_true")
    p = sub.add_parser("patch", parents=[common])
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("-p", "--patch", required=True)
    p.add_argument("--type", dest="patch_type", choices=["merge", "strategic", "json"],
                   default="merge")
    p = sub.add_parser("taint", parents=[common])
    p.add_argument("resource", help="must be nodes")
    p.add_argument("name")
    p.add_argument("specs", nargs="+", help="key=value:Effect or key:Effect-")
    p = sub.add_parser("expose", parents=[common])
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--target-port", type=int, default=0)
    p.add_argument("--type", dest="svc_type", default="ClusterIP")
    p.add_argument("--name", dest="svc_name", default="")
    p = sub.add_parser("run", parents=[common])
    p.add_argument("name")
    p.add_argument("--image", required=True)
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--restart", choices=["Always", "OnFailure", "Never"], default="Always")
    p.add_argument("--labels", default="")
    p = sub.add_parser("autoscale", parents=[common])
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("--min", dest="min_replicas", type=int, default=1)
    p.add_argument("--max", dest="max_replicas", type=int, required=True)
    p.add_argument("--cpu-percent", type=int, default=80)
    p = sub.add_parser("set", parents=[common])
    p.add_argument("what", choices=["image", "resources", "env",
                                    "selector", "serviceaccount", "sa",
                                    "subject"])
    p.add_argument("resource")  # "deployment" or "deployment/NAME"
    p.add_argument("name", nargs="?")
    p.add_argument("pairs", nargs="*", help="container=image pairs (set image)")
    p.add_argument("--requests", default="")
    p.add_argument("--limits", default="")
    p.add_argument("--user", action="append", default=[])
    p.add_argument("--group", action="append", default=[])
    p.add_argument("--serviceaccount", action="append", default=[],
                   help="ns:name (set subject)")
    p = sub.add_parser("auth", parents=[common])
    p.add_argument("action", choices=["can-i"])
    p.add_argument("auth_verb")
    p.add_argument("auth_resource")
    p.add_argument("auth_name", nargs="?", default="")
    sub.add_parser("api-versions", parents=[common])
    sub.add_parser("api-resources", parents=[common])
    sub.add_parser("version", parents=[common])
    p = sub.add_parser("cluster-info", parents=[common])
    p.add_argument("action", nargs="?", default="", choices=["", "dump"])
    p.add_argument("--output-directory", default="")
    p = sub.add_parser("replace", parents=[common])
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--force", action="store_true")
    p = sub.add_parser("convert", parents=[common])
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--output-version", required=True,
                   help="e.g. apps/v1beta1, extensions/v1beta1")
    p = sub.add_parser("completion", parents=[common])
    p.add_argument("shell", choices=["bash", "zsh"])
    p = sub.add_parser("config", parents=[common])
    p.add_argument("config_args", nargs="*")
    p = sub.add_parser("wait", parents=[common])
    p.add_argument("resource")  # "pod/NAME" or "pod NAME"
    p.add_argument("name", nargs="?")
    p.add_argument("--for", dest="condition", required=True,
                   help="condition=TYPE or delete")
    p.add_argument("--timeout", type=float, default=30.0)
    p = sub.add_parser("attach", parents=[common])
    p.add_argument("name")
    p.add_argument("-c", "--container", default="")
    p = sub.add_parser("cp", parents=[common])
    p.add_argument("src")
    p.add_argument("dst")
    p.add_argument("-c", "--container", default="")
    p = sub.add_parser("port-forward", parents=[common])
    p.add_argument("name")
    p.add_argument("ports", help="LOCAL:REMOTE or PORT")
    p = sub.add_parser("proxy", parents=[common])
    p.add_argument("--port", type=int, default=0)
    p = sub.add_parser("explain", parents=[common])
    p.add_argument("resource", help="RESOURCE[.field...]")
    p = sub.add_parser("edit", parents=[common])
    p.add_argument("resource")
    p.add_argument("name")
    p = sub.add_parser("rolling-update", parents=[common])
    p.add_argument("old")
    p.add_argument("--image", required=True)
    p.add_argument("--new-name", default="")

    # plugin dispatch BEFORE argparse rejects the verb: the FIRST token
    # (plugin convention — never a flag's value, never a later positional)
    # names either a built-in or a kubectl-<verb> plugin
    ALL_VERBS[:] = list(sub.choices)

    raw_args = list(argv) if argv is not None else sys.argv[1:]
    if raw_args and not raw_args[0].startswith("-") and raw_args[0] not in sub.choices:
        rc = _run_plugin(raw_args[0], raw_args[1:], out or sys.stdout)
        if rc is not None:
            return rc

    args = parser.parse_args(argv)
    server = getattr(args, "server", "http://127.0.0.1:8080")
    token = getattr(args, "token", None)
    namespace = getattr(args, "namespace", None)
    output = getattr(args, "output", "")
    if clientset is not None:
        cs = clientset
    elif getattr(args, "kubeconfig", None) and args.verb != "config":
        # ("config" manages a kubectl-format kubeconfig FILE; its
        # --kubeconfig names the file to edit, not a connection.)
        # The kubeadm kubeconfig-phase artifact: server + CA pin +
        # client cert; EVERY explicit flag overrides its field.  The
        # merge itself lives in daemon.remote_clientset — one copy.
        from ..daemon import remote_clientset

        try:
            cs = remote_clientset(
                getattr(args, "server", None),
                token=token,
                kubeconfig=args.kubeconfig,
                ca_file=getattr(args, "ca_file", None),
                client_cert=getattr(args, "client_cert", None),
                client_key=getattr(args, "client_key", None))
        except (ValueError, OSError) as e:
            (out or sys.stdout).write(f"error: --kubeconfig: {e}\n")
            return 1
    else:
        cs = Clientset(RemoteStore(
            server, token=token,
            ca_file=getattr(args, "ca_file", None),
            client_cert=getattr(args, "client_cert", None),
            client_key=getattr(args, "client_key", None)))
    k = Kubectl(cs, out=out)
    if args.verb == "get":
        if getattr(args, "watch", False):
            if args.name:
                k.out.write("error: -w does not take a name\n")
                return 1
            return k.get_watch(args.resource, namespace, args.selector,
                               args.watch_timeout)
        return k.get(args.resource, args.name, namespace, output, args.selector,
                     args.sort_by, args.show_labels, args.no_headers)
    if args.verb == "describe":
        return k.describe(args.resource, args.name, namespace)
    if args.verb == "create":
        if args.filename:
            return k.create(args.filename)
        what, name, extra = args.what, args.gen_name, args.gen_extra
        if not what or not name:
            k.out.write("error: create needs -f FILE or a generator "
                        "(namespace|configmap|secret|serviceaccount|quota|"
                        "service|deployment|role|rolebinding|clusterrole|"
                        "clusterrolebinding|pdb) and a name\n")
            return 1
        svc_type = "ClusterIP"
        if what == "secret":
            # "secret generic NAME" — the subtype token precedes the name
            if not extra:
                k.out.write("error: usage: create secret generic NAME\n")
                return 1
            if name != "generic":
                k.out.write(f"error: unsupported secret type {name!r} "
                            f"(only generic)\n")
                return 1
            name = extra
        elif what == "service":
            if not extra:
                k.out.write("error: usage: create service "
                            "clusterip|nodeport|loadbalancer NAME\n")
                return 1
            svc_type = {"clusterip": "ClusterIP", "nodeport": "NodePort",
                        "loadbalancer": "LoadBalancer"}.get(name.lower(), "")
            if not svc_type:
                k.out.write(f"error: unknown service type {name!r}\n")
                return 1
            name = extra
        if what == "pdb":
            what = "poddisruptionbudget"
        return k.create_resource(what, name, namespace, args.from_literal,
                                 args.from_file, args.hard, args.tcp,
                                 args.secret_type, svc_type,
                                 verbs=args.rbac_verb,
                                 resources=args.rbac_resource,
                                 role=args.role, clusterrole=args.clusterrole,
                                 users=args.user, groups=args.group,
                                 serviceaccounts=args.serviceaccount,
                                 selector=getattr(args, "selector", ""),
                                 min_available=args.min_available,
                                 image=args.image, replicas=args.replicas)
    if args.verb == "certificate":
        return k.certificate(args.action, args.name)
    if args.verb == "apply":
        sv = getattr(args, "subverb", None)
        if sv in ("view-last-applied", "set-last-applied", "edit-last-applied"):
            if sv == "set-last-applied":
                if not args.filename:
                    k.out.write("error: set-last-applied requires -f FILE\n")
                    return 1
                return k.apply_set_last_applied(args.filename,
                                                args.create_annotation)
            target = args.target or ""
            tname = args.target_name
            if "/" in target:
                target, tname = target.split("/", 1)
            if not target or not tname:
                k.out.write(f"error: {sv} requires RESOURCE/NAME\n")
                return 1
            if sv == "view-last-applied":
                return k.apply_view_last_applied(
                    target, tname, namespace,
                    getattr(args, "output", None) or "yaml")
            return k.apply_edit_last_applied(target, tname, namespace)
        if sv is not None:
            # a typo'd subverb must NEVER fall through to a live apply,
            # -f or not — that would mutate objects the user only meant
            # to annotate
            k.out.write(f"error: unknown apply subcommand {sv!r}\n")
            return 1
        if args.filename is None:
            k.out.write("error: apply requires -f FILE\n")
            return 1
        return k.apply(args.filename, getattr(args, "prune", False),
                       getattr(args, "selector", ""))
    if args.verb == "delete":
        if not args.name and not args.selector:
            k.out.write("error: a name or -l selector is required\n")
            return 1
        return k.delete(args.resource, args.name, namespace, args.selector,
                        getattr(args, "cascade", "background"))
    if args.verb == "scale":
        return k.scale(args.resource, args.name, args.replicas, namespace)
    if args.verb == "cordon":
        return k.cordon(args.name, True)
    if args.verb == "uncordon":
        return k.cordon(args.name, False)
    if args.verb == "drain":
        return k.drain(args.name, getattr(args, "ignore_daemonsets", False),
                       getattr(args, "force", False))
    if args.verb == "top":
        if args.what == "pods":
            return k.top_pods(namespace)
        return k.top_nodes()
    if args.verb == "logs":
        if args.follow:
            return k.logs_follow(args.name, namespace, args.container,
                                 args.follow_timeout, tail=args.tail)
        return k.logs(args.name, namespace, args.container, args.tail)
    if args.verb == "exec":
        cmd = list(args.command)
        if cmd and cmd[0] == "--":
            cmd = cmd[1:]  # only the FIRST separator belongs to kubectl
        if not cmd:
            k.out.write("error: command required after --\n")
            return 1
        return k.exec(args.name, cmd, namespace, args.container)
    if args.verb == "rollout":
        res = args.resource
        name = args.name
        if name is None and "/" in res:
            res, name = res.split("/", 1)
        if _resolve(res)[1] != "Deployment" or not name:
            k.out.write("error: rollout supports deployment/NAME\n")
            return 1
        if args.action == "status":
            return k.rollout_status(name, namespace)
        if args.action == "history":
            return k.rollout_history(name, namespace)
        if args.action in ("pause", "resume"):
            return k.rollout_pause(name, args.action == "pause", namespace)
        return k.rollout_undo(name, namespace, args.to_revision)
    if args.verb in ("label", "annotate"):
        fn = k.label if args.verb == "label" else k.annotate
        res, name, pairs = args.resource, args.name, list(args.pairs)
        if "/" in res:  # TYPE/NAME form: the name slot holds a pair
            res, _, name2 = res.partition("/")
            if name is not None:
                pairs.insert(0, name)
            name = name2
        elif name is not None and (args.all_resources or args.selector) \
                and ("=" in name or name.endswith("-")):
            # bulk form: every positional after TYPE is a pair
            pairs.insert(0, name)
            name = None
        return fn(res, name, pairs, namespace, args.overwrite,
                  args.resource_version, args.selector, args.all_resources)
    if args.verb == "patch":
        return k.patch(args.resource, args.name, args.patch, namespace, args.patch_type)
    if args.verb == "taint":
        if _resolve(args.resource)[1] != "Node":
            k.out.write("error: taint supports nodes only\n")
            return 1
        return k.taint(args.name, args.specs)
    if args.verb == "expose":
        return k.expose(args.resource, args.name, args.port, args.target_port,
                        args.svc_type, args.svc_name, namespace)
    if args.verb == "run":
        return k.run(args.name, args.image, args.replicas, args.restart,
                     namespace, args.labels)
    if args.verb == "autoscale":
        return k.autoscale(args.resource, args.name, args.min_replicas,
                           args.max_replicas, args.cpu_percent, namespace)
    if args.verb == "set":
        res, name = args.resource, args.name
        pairs = list(args.pairs)
        if "/" in res:
            # "set ... deployment/web [spec...]": any name-slot token is a
            # spec ("c=img", "KEY=VALUE", or an env "KEY-" removal)
            if name is not None:
                pairs.insert(0, name)
            res, name = res.split("/", 1)
        if not name:
            k.out.write("error: set requires RESOURCE/NAME\n")
            return 1
        if args.what == "image":
            return k.set_image(res, name, pairs, namespace)
        if args.what == "env":
            return k.set_env(res, name, pairs, namespace)
        if args.what == "selector":
            if not pairs:
                k.out.write("error: set selector requires key=value[,...]\n")
                return 1
            return k.set_selector(res, name, ",".join(pairs), namespace)
        if args.what in ("serviceaccount", "sa"):
            if not pairs:
                k.out.write("error: set serviceaccount requires a name\n")
                return 1
            return k.set_serviceaccount(res, name, pairs[0], namespace)
        if args.what == "subject":
            return k.set_subject(res, name, args.user, args.group,
                                 args.serviceaccount, namespace)
        return k.set_resources(res, name, args.requests, args.limits, namespace)
    if args.verb == "auth":
        return k.auth_can_i(args.auth_verb, args.auth_resource, args.auth_name, namespace)
    if args.verb == "api-versions":
        return k.api_versions()
    if args.verb == "api-resources":
        return k.api_resources()
    if args.verb == "version":
        return k.version()
    if args.verb == "cluster-info":
        if getattr(args, "action", "") == "dump":
            return k.cluster_info_dump(args.output_directory)
        return k.cluster_info()
    if args.verb == "replace":
        return k.replace(args.filename, args.force)
    if args.verb == "convert":
        return k.convert(args.filename, args.output_version)
    if args.verb == "completion":
        return k.completion(args.shell)
    if args.verb == "config":
        return k.config(args.config_args,
                        getattr(args, "kubeconfig", None))
    if args.verb == "wait":
        res, name = args.resource, args.name
        if name is None and "/" in res:
            res, name = res.split("/", 1)
        if not name:
            k.out.write("error: wait requires RESOURCE/NAME\n")
            return 1
        return k.wait_for(res, name, args.condition, namespace, args.timeout)
    if args.verb == "attach":
        return k.attach(args.name, namespace, args.container)
    if args.verb == "cp":
        return k.cp(args.src, args.dst, namespace, args.container)
    if args.verb == "port-forward":
        fwd = k.port_forward(args.name, args.ports, namespace)
        if fwd is None:
            return 1
        try:
            import time as _time

            while True:  # serve until interrupted (reference behavior)
                _time.sleep(3600)
        except KeyboardInterrupt:
            fwd.stop()
            return 0
    if args.verb == "proxy":
        httpd = k.proxy(args.port)
        if httpd is None:
            return 1
        try:
            import time as _time

            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            httpd.shutdown()
            return 0
    if args.verb == "explain":
        return k.explain(args.resource)
    if args.verb == "edit":
        return k.edit(args.resource, args.name, namespace)
    if args.verb == "rolling-update":
        return k.rolling_update(args.old, args.image, namespace, args.new_name)
    return 2


def _run_plugin(verb: str, rest: list[str], out) -> Optional[int]:
    """kubectl plugin mechanism (reference ``pkg/kubectl/plugins``): an
    unknown verb resolves to an executable ``kubectl-<verb>`` on
    KUBECTL_PLUGINS_PATH (then PATH) and runs with the remaining args."""
    import os
    import shutil
    import subprocess

    name = f"kubectl-{verb}"
    candidate = None
    for d in os.environ.get("KUBECTL_PLUGINS_PATH", "").split(os.pathsep):
        if d and os.path.isfile(os.path.join(d, name)) and os.access(
                os.path.join(d, name), os.X_OK):
            candidate = os.path.join(d, name)
            break
    candidate = candidate or shutil.which(name)
    if candidate is None:
        return None
    proc = subprocess.run([candidate, *rest], capture_output=True, text=True)
    out.write(proc.stdout)
    if proc.stderr:
        out.write(proc.stderr)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
