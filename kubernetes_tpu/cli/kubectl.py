"""kubectl-equivalent CLI.

Capability of the reference's kubectl core verbs (``pkg/kubectl``, SURVEY.md
§2.8) at the depth this control plane serves:

  get / describe / create -f / apply -f / delete / scale / cordon /
  uncordon / drain / events / top nodes

``apply`` is declarative create-or-update keyed on the last-applied
configuration annotation (the essential of the reference's 3-way strategic
merge, ``cmd/apply.go``): unchanged manifests are left alone, changed ones
update spec/labels while preserving cluster-owned fields.  ``drain``
cordons then evicts (``cmd/drain.go``).  Manifests are YAML or JSON, one or
many documents.

Speaks to an API server over HTTP (``--server``), or to an in-process
clientset when embedded (tests, single-binary demos).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

import yaml

from ..api import types as api
from ..api.types import kind_for_plural
from ..client.clientset import Clientset
from ..client.remote import RemoteStore
from ..store.store import AlreadyExistsError, NotFoundError

LAST_APPLIED = "kubectl.kubernetes.io/last-applied-configuration"


def _parse_selector(spec: str):
    """kubectl's equality selector forms: "k=v", "k==v", "k!=v", comma
    separated.  Returns [(key, op, value)] or None on a malformed (or
    effectively empty) selector — an empty selector must NOT silently
    mean match-all, because delete -l rides on it."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            op = "!="
        elif "==" in part:
            k, v = part.split("==", 1)
            op = "="
        elif "=" in part:
            k, v = part.split("=", 1)
            op = "="
        else:
            return None
        k, v = k.strip(), v.strip()
        if not k:
            return None
        out.append((k, op, v))
    return out or None


def _labels_match(obj, want: list) -> bool:
    labels = obj.meta.labels
    for k, op, v in want:
        if op == "=" and labels.get(k) != v:
            return False
        if op == "!=" and labels.get(k) == v:
            return False
    return True
REVISION_ANNOTATION = api.DEPLOYMENT_REVISION_ANNOTATION


def _jsonpath(doc, expr: str) -> list:
    """The jsonpath subset ``get -o jsonpath=`` actually gets used for
    (reference ``pkg/util/jsonpath``): ``{.a.b}``, ``{.items[2].x}``, and
    ``{.items[*].x}`` fan-out.  Multiple ``{...}`` groups concatenate."""
    import re

    out: list = []
    exprs = re.findall(r"\{([^}]*)\}", expr) or [expr]
    for e in exprs:
        nodes = [doc]
        for part in [p for p in e.strip().lstrip(".").split(".") if p]:
            m = re.fullmatch(r"([^\[\]]*)(?:\[(\*|-?\d+)\])?", part)
            if m is None:
                raise ValueError(f"bad jsonpath segment {part!r}")
            field_name, idx = m.group(1), m.group(2)
            next_nodes = []
            for n in nodes:
                v = n[field_name] if field_name else n
                if idx is None:
                    next_nodes.append(v)
                elif idx == "*":
                    next_nodes.extend(v)
                else:
                    next_nodes.append(v[int(idx)])
            nodes = next_nodes
        out.extend(nodes)
    return out

# kind -> plural resource name, from the one type registry (RESTMapper
# analogue) — new kinds (incl. CRDs) become kubectl-addressable on import.
KIND_TO_RESOURCE = api.KIND_PLURALS

_SHORT_NAMES = {
    "po": "pods",
    "no": "nodes",
    "svc": "services",
    "rs": "replicasets",
    "deploy": "deployments",
    "ev": "events",
    "ns": "namespaces",
    "ds": "daemonsets",
    "sts": "statefulsets",
    "cj": "cronjobs",
    "hpa": "horizontalpodautoscalers",
    "pdb": "poddisruptionbudgets",
    "pv": "persistentvolumes",
    "pvc": "persistentvolumeclaims",
    "sa": "serviceaccounts",
    "quota": "resourcequotas",
    "cm": "configmaps",
    "ep": "endpoints",
    "limits": "limitranges",
    "pc": "priorityclasses",
    "csr": "certificatesigningrequests",
}


def _resource_aliases() -> dict[str, str]:
    """plural, singular (kind lowercased), and short names all resolve."""
    out = dict(_SHORT_NAMES)
    for kind, plural in KIND_TO_RESOURCE.items():
        out[plural] = plural
        out[kind.lower()] = plural
    return out


def _resolve(resource: str):
    # Alias -> (plural, kind), computed per call so kinds registered
    # after module import (CRD-style) resolve immediately.
    plural = _resource_aliases().get(resource, resource)
    return plural, kind_for_plural(plural)


class Kubectl:
    def __init__(self, clientset: Clientset, out=None):
        self.cs = clientset
        self.out = out or sys.stdout

    def _print(self, *cols_rows) -> None:
        rows = [r for r in cols_rows]
        widths = [max(len(str(r[i])) for r in rows) for i in range(len(rows[0]))]
        for r in rows:
            self.out.write("  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip() + "\n")

    # -- get ---------------------------------------------------------------
    def get(self, resource: str, name: Optional[str] = None, namespace: Optional[str] = None,
            output: str = "", selector: str = "") -> int:
        resource, kind = _resolve(resource)
        if kind is None:
            self.out.write(f"error: unknown resource {resource!r}\n")
            return 1
        client = self.cs.client_for(kind)
        if name:
            if selector:
                self.out.write("error: a name cannot be combined with -l\n")
                return 1
            try:
                objs = [client.get(name, namespace)]
            except NotFoundError:
                self.out.write(f'Error: {resource} "{name}" not found\n')
                return 1
        else:
            objs, _ = client.list(namespace)
            if selector:
                want = _parse_selector(selector)
                if want is None:
                    self.out.write(f"error: bad selector {selector!r}\n")
                    return 1
                objs = [o for o in objs if _labels_match(o, want)]
        if output == "json":
            docs = [o.to_dict() for o in objs]
            self.out.write(json.dumps(docs[0] if name else {"items": docs}, indent=2) + "\n")
            return 0
        if output == "yaml":
            docs = [o.to_dict() for o in objs]
            self.out.write(yaml.safe_dump(docs[0] if name else {"items": docs}))
            return 0
        if output and not output.startswith("jsonpath="):
            self.out.write(f"error: unsupported output format {output!r}\n")
            return 1
        if output.startswith("jsonpath="):
            docs = [o.to_dict() for o in objs]
            doc = docs[0] if name else {"items": docs}
            try:
                values = _jsonpath(doc, output[len("jsonpath="):])
            except (KeyError, IndexError, TypeError, ValueError) as e:
                self.out.write(f"error: jsonpath: {e}\n")
                return 1
            self.out.write(" ".join(str(v) for v in values) + "\n")
            return 0
        rows = [self._headers(kind)]
        for o in objs:
            rows.append(self._row(kind, o))
        self._print(*rows)
        return 0

    def _headers(self, kind: str):
        return {
            "Pod": ("NAME", "STATUS", "NODE", "PRIORITY"),
            "Node": ("NAME", "READY", "UNSCHEDULABLE", "CPU", "MEMORY"),
            "Deployment": ("NAME", "DESIRED", "CURRENT", "UP-TO-DATE", "READY"),
            "ReplicaSet": ("NAME", "DESIRED", "CURRENT", "READY"),
            "Service": ("NAME", "SELECTOR"),
            "Event": ("OBJECT", "TYPE", "REASON", "MESSAGE"),
            "Job": ("NAME", "ACTIVE", "SUCCEEDED", "FAILED"),
            "DaemonSet": ("NAME", "DESIRED", "CURRENT", "READY"),
            "StatefulSet": ("NAME", "DESIRED", "CURRENT", "READY"),
            "Namespace": ("NAME", "STATUS"),
        }.get(kind, ("NAME",))

    def _row(self, kind: str, o):
        if kind == "Pod":
            return (o.meta.name, o.status.phase, o.spec.node_name or "<none>", o.spec.priority)
        if kind == "Node":
            ready = o.status.condition(api.NODE_READY)
            return (
                o.meta.name,
                ready.status if ready else "Unknown",
                o.spec.unschedulable,
                str(o.status.allocatable.get(api.CPU, "")),
                str(o.status.allocatable.get(api.MEMORY, "")),
            )
        if kind == "Deployment":
            return (o.meta.name, o.replicas, o.status_replicas, o.status_updated_replicas,
                    o.status_ready_replicas)
        if kind == "ReplicaSet":
            return (o.meta.name, o.replicas, o.status_replicas, o.status_ready_replicas)
        if kind == "Service":
            return (o.meta.name, ",".join(f"{k}={v}" for k, v in o.selector.items()))
        if kind == "Event":
            return (o.involved_key, o.type, o.reason, o.message[:80])
        if kind == "Job":
            return (o.meta.name, o.status_active, o.status_succeeded, o.status_failed)
        if kind == "DaemonSet":
            return (o.meta.name, o.status_desired, o.status_current, o.status_ready)
        if kind == "StatefulSet":
            return (o.meta.name, o.replicas, o.status_current_replicas, o.status_ready_replicas)
        if kind == "Namespace":
            return (o.meta.name, o.phase)
        return (o.meta.name,)

    # -- describe ----------------------------------------------------------
    def describe(self, resource: str, name: str, namespace: Optional[str] = None) -> int:
        resource, kind = _resolve(resource)
        try:
            obj = self.cs.client_for(kind).get(name, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(yaml.safe_dump(obj.to_dict(), sort_keys=False))
        events, _ = self.cs.events.list()
        related = [e for e in events if e.involved_key.endswith(f"/{name}") or e.involved_key == name]
        if related:
            self.out.write("Events:\n")
            for e in related[-10:]:
                self.out.write(f"  {e.type}\t{e.reason}\t{e.message}\n")
        return 0

    # -- create / apply / delete ------------------------------------------
    def _load_manifests(self, path: str) -> list[dict]:
        from ..api.scheme import convert_to_internal

        text = sys.stdin.read() if path == "-" else open(path).read()
        # versioned wire documents (apps/v1beta1, extensions/v1beta1,
        # batch/v2alpha1, ...) decode through the scheme — reference-era
        # YAML applies unchanged
        return [convert_to_internal(d) for d in yaml.safe_load_all(text) if d]

    def create(self, filename: str) -> int:
        rc = 0
        for doc in self._load_manifests(filename):
            kind = doc.get("kind", "")
            if kind not in KIND_TO_RESOURCE:
                self.out.write(f"error: unknown kind {kind!r} in manifest\n")
                rc = 1
                continue
            try:
                obj = self.cs.client_for(kind).create(api.from_dict(doc))
                self.out.write(f"{KIND_TO_RESOURCE[kind]}/{obj.meta.name} created\n")
            except AlreadyExistsError:
                self.out.write(f"Error: {kind} already exists\n")
                rc = 1
        return rc

    def apply(self, filename: str) -> int:
        for doc in self._load_manifests(filename):
            kind = doc.get("kind", "")
            if kind not in KIND_TO_RESOURCE:
                self.out.write(f"error: unknown kind {kind!r} in manifest\n")
                return 1
            client = self.cs.client_for(kind)
            manifest = json.dumps(doc, sort_keys=True)
            meta = doc.get("metadata") or {}
            name = meta.get("name", "")
            ns = meta.get("namespace", client.default_namespace)
            try:
                cur = client.get(name, ns)
            except (NotFoundError, KeyError):
                obj = api.from_dict(doc)
                obj.meta.annotations[LAST_APPLIED] = manifest
                client.create(obj)
                self.out.write(f"{KIND_TO_RESOURCE[kind]}/{name} created\n")
                continue
            if cur.meta.annotations.get(LAST_APPLIED) == manifest:
                self.out.write(f"{KIND_TO_RESOURCE[kind]}/{name} unchanged\n")
                continue

            def _merge(live):
                desired = api.from_dict(doc)
                desired.meta = live.meta  # preserve cluster-owned identity
                desired.meta.labels = dict((doc.get("metadata") or {}).get("labels") or {})
                desired.meta.annotations = dict(live.meta.annotations)
                desired.meta.annotations[LAST_APPLIED] = manifest
                if hasattr(live, "status"):
                    desired.status = live.status  # status is cluster-owned
                return desired

            client.guaranteed_update(name, _merge, ns)
            self.out.write(f"{KIND_TO_RESOURCE[kind]}/{name} configured\n")
        return 0

    def delete(self, resource: str, name: Optional[str], namespace: Optional[str] = None,
               selector: str = "") -> int:
        if name and selector:
            self.out.write("error: a name cannot be combined with -l\n")
            return 1
        if selector and not name:
            resource2, kind = _resolve(resource)
            if kind is None:
                self.out.write(f"error: unknown resource {resource!r}\n")
                return 1
            want = _parse_selector(selector)
            if want is None:
                self.out.write(f"error: bad selector {selector!r}\n")
                return 1
            client = self.cs.client_for(kind)
            # scope like every other verb: the default namespace, never
            # all-namespaces implicitly (delete is irreversible)
            ns_scope = namespace if namespace is not None else client.default_namespace
            victims = [o for o in client.list(ns_scope)[0] if _labels_match(o, want)]
            for o in victims:
                try:
                    client.delete(o.meta.name, o.meta.namespace)
                    self.out.write(f"{resource2}/{o.meta.name} deleted\n")
                except NotFoundError:
                    pass
            if not victims:
                self.out.write("No resources found\n")
            return 0
        return self._delete_one(resource, name, namespace)

    def _delete_one(self, resource: str, name: str, namespace: Optional[str] = None) -> int:
        resource, kind = _resolve(resource)
        try:
            self.cs.client_for(kind).delete(name, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} deleted\n")
        return 0

    def top_pods(self, namespace: Optional[str] = None) -> int:
        """``kubectl top pods``: per-pod memory from each node's kubelet
        stats endpoint (the heapster/metricsutil path at this depth)."""
        import json as _json
        import urllib.request

        from concurrent.futures import ThreadPoolExecutor

        rows = [("NAME", "NODE", "MEMORY")]
        ns = namespace or "default"
        nodes = [n for n in self.cs.nodes.list()[0] if n.status.kubelet_url]

        def fetch(node):
            try:
                with urllib.request.urlopen(
                    f"{node.status.kubelet_url}/stats/summary", timeout=5
                ) as r:
                    return node, _json.loads(r.read()), None
            except Exception as e:  # noqa: BLE001 - reported per node below
                return node, None, e

        unreachable = []
        with ThreadPoolExecutor(max_workers=16) as pool:
            for node, summary, err in pool.map(fetch, nodes):
                if err is not None:
                    unreachable.append((node.meta.name, err))
                    continue
                for entry in summary.get("pods", []):
                    ref = entry.get("podRef") or {}
                    if ref.get("namespace") != ns:
                        continue
                    mib = (entry.get("memory") or {}).get("usageBytes", 0) // (1 << 20)
                    rows.append((ref.get("name", ""), node.meta.name, f"{mib}Mi"))
        self._print(*rows)
        for name, err in unreachable:
            self.out.write(f"warning: could not fetch stats from node {name}: {err}\n")
        return 0 if len(rows) > 1 or not unreachable else 1

    # -- rollout (cmd/rollout, rollback.go) --------------------------------
    def _dep_and_rses(self, name: str, namespace: Optional[str]):
        dep = self.cs.deployments.get(name, namespace)
        rses = []
        for rs in self.cs.replicasets.list(namespace or "default")[0]:
            ref = rs.meta.controller_ref()
            if ref is not None and ref.kind == "Deployment" and ref.uid == dep.meta.uid:
                rses.append(rs)
        return dep, rses

    def rollout_status(self, name: str, namespace: Optional[str] = None) -> int:
        """``kubectl rollout status deployment NAME``: 0 when the rollout
        is complete, 1 while in progress (the reference polls; one shot
        here — loops live in the caller)."""
        try:
            dep, _ = self._dep_and_rses(name, namespace)
        except NotFoundError:
            self.out.write(f'Error: deployment "{name}" not found\n')
            return 1
        # completion also requires the CURRENT template's RS to be fully
        # rolled out — aggregate counters alone go stale the instant the
        # spec changes (reference guards with observedGeneration +
        # updatedReplicas-of-current-template)
        from ..controllers.deployment import template_hash

        want_hash = template_hash(dep.template)
        cur_rs = next(
            (rs for rs in self._dep_and_rses(name, namespace)[1]
             if rs.meta.labels.get("pod-template-hash") == want_hash),
            None,
        )
        if (
            cur_rs is not None
            and cur_rs.status_ready_replicas >= dep.replicas
            and dep.status_updated_replicas >= dep.replicas
            and dep.status_ready_replicas >= dep.replicas
            and dep.status_replicas == dep.replicas
        ):
            self.out.write(f'deployment "{name}" successfully rolled out\n')
            return 0
        self.out.write(
            f"Waiting for rollout: {dep.status_updated_replicas} of "
            f"{dep.replicas} updated, {dep.status_ready_replicas} ready\n"
        )
        return 1

    def rollout_history(self, name: str, namespace: Optional[str] = None) -> int:
        try:
            dep, rses = self._dep_and_rses(name, namespace)
        except NotFoundError:
            self.out.write(f'Error: deployment "{name}" not found\n')
            return 1
        self.out.write(f"deployment/{name}\nREVISION  REPLICASET\n")
        for rs in sorted(
            rses, key=lambda r: int(r.meta.annotations.get(REVISION_ANNOTATION, "0"))
        ):
            rev = rs.meta.annotations.get(REVISION_ANNOTATION, "0")
            self.out.write(f"{rev:<9} {rs.meta.name}\n")
        return 0

    def rollout_undo(self, name: str, namespace: Optional[str] = None,
                     to_revision: int = 0) -> int:
        """``rollback.go``: re-apply the target revision's template (the
        previous one by default); the controller's hash matching then
        treats that RS as new again and bumps its revision."""
        try:
            dep, rses = self._dep_and_rses(name, namespace)
        except NotFoundError:
            self.out.write(f'Error: deployment "{name}" not found\n')
            return 1
        by_rev = {
            int(rs.meta.annotations.get(REVISION_ANNOTATION, "0")): rs for rs in rses
        }
        if not by_rev:
            self.out.write("error: no rollout history\n")
            return 1
        if to_revision:
            target = by_rev.get(to_revision)
            if target is None:
                self.out.write(f"error: revision {to_revision} not found\n")
                return 1
        else:
            revs = sorted(by_rev)
            if len(revs) < 2:
                self.out.write("error: no previous revision\n")
                return 1
            target = by_rev[revs[-2]]

        template = api.PodTemplateSpec.from_dict(target.template.to_dict())
        template.labels.pop("pod-template-hash", None)

        def _rollback(cur):
            cur.template = template
            return cur

        self.cs.deployments.guaranteed_update(name, _rollback, namespace)
        self.out.write(f"deployment/{name} rolled back\n")
        return 0

    def _kubelet_target(self, name: str, ns: str, container: str):
        """In-proc path resolution: pod -> node -> kubelet URL + container.
        Returns (url_base, container) or None after printing the error."""
        try:
            pod = self.cs.pods.get(name, ns)
        except NotFoundError:
            self.out.write(f'Error: pod "{name}" not found\n')
            return None
        if not pod.spec.node_name:
            self.out.write("error: pod is not scheduled yet\n")
            return None
        try:
            node = self.cs.nodes.get(pod.spec.node_name)
        except NotFoundError:
            self.out.write(f'error: node "{pod.spec.node_name}" not found\n')
            return None
        if not node.status.kubelet_url:
            self.out.write("error: node exposes no kubelet endpoint\n")
            return None
        c = container or (pod.spec.containers[0].name if pod.spec.containers else "")
        return node.status.kubelet_url, c, pod.spec.node_name

    def logs(self, name: str, namespace: Optional[str] = None,
             container: str = "", tail: int = 0) -> int:
        """``kubectl logs`` via the pod/log subresource (apiserver proxies
        to the owning node's kubelet read API)."""
        ns = namespace or "default"
        base = getattr(self.cs.store, "base_url", None)
        if base is None:
            # in-proc clientset: reach the kubelet URL directly
            resolved = self._kubelet_target(name, ns, container)
            if resolved is None:
                return 1
            kubelet_url, c, _ = resolved
            url = f"{kubelet_url}/containerLogs/{ns}/{name}/{c}"
            if tail:
                url += f"?tailLines={tail}"
        else:
            url = f"{base}/api/v1/namespaces/{ns}/pods/{name}/log"
            sep = "?"
            if container:
                url += f"{sep}container={container}"
                sep = "&"
            if tail:
                url += f"{sep}tailLines={tail}"
        import urllib.error
        import urllib.request

        req = urllib.request.Request(url)
        token = getattr(self.cs.store, "token", None)
        if base is not None and token:
            # the other verbs authenticate via RemoteStore; this direct
            # fetch must carry the same credential
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                self.out.write(r.read().decode())
            return 0
        except urllib.error.HTTPError as e:
            self.out.write(f"error: {e.read().decode()}\n")
            return 1
        except Exception as e:
            self.out.write(f"error: {e}\n")
            return 1

    def exec(self, name: str, command: list[str], namespace: Optional[str] = None,
             container: str = "") -> int:
        """``kubectl exec POD -- cmd...`` via the pods/exec subresource."""
        import json as _json
        import urllib.error
        import urllib.request

        ns = namespace or "default"
        base = getattr(self.cs.store, "base_url", None)
        exec_node = None
        if base is None:
            resolved = self._kubelet_target(name, ns, container)
            if resolved is None:
                return 1
            kubelet_url, c, exec_node = resolved
            url = f"{kubelet_url}/exec/{ns}/{name}/{c}"
        else:
            url = f"{base}/api/v1/namespaces/{ns}/pods/{name}/exec"
            if container:
                url += f"?container={container}"
        req = urllib.request.Request(
            url, data=_json.dumps({"command": command}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        if base is not None:
            token = getattr(self.cs.store, "token", None)
            if token:
                req.add_header("Authorization", f"Bearer {token}")
        else:
            # direct kubelet path: mint the cluster-key exec credential
            from ..auth.authn import kubelet_exec_token

            req.add_header("Authorization", f"Bearer {kubelet_exec_token(exec_node)}")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                out = _json.loads(r.read())
        except urllib.error.HTTPError as e:
            self.out.write(f"error: {e.read().decode()}\n")
            return 1
        except Exception as e:
            self.out.write(f"error: {e}\n")
            return 1
        if out.get("stdout"):
            self.out.write(out["stdout"] + ("\n" if not out["stdout"].endswith("\n") else ""))
        return int(out.get("exitCode", 0))

    # -- scale / cordon / drain -------------------------------------------
    def scale(self, resource: str, name: str, replicas: int, namespace: Optional[str] = None) -> int:
        resource, kind = _resolve(resource)
        if kind not in ("Deployment", "ReplicaSet"):
            self.out.write(f"error: cannot scale {resource}\n")
            return 1

        def _scale(obj):
            obj.replicas = replicas
            return obj

        try:
            self.cs.client_for(kind).guaranteed_update(name, _scale, namespace)
        except (NotFoundError, KeyError):
            self.out.write(f'Error: {resource} "{name}" not found\n')
            return 1
        self.out.write(f"{resource}/{name} scaled to {replicas}\n")
        return 0

    def cordon(self, name: str, on: bool = True) -> int:
        def _set(node):
            node.spec.unschedulable = on
            return node

        try:
            self.cs.nodes.guaranteed_update(name, _set, "")
        except (NotFoundError, KeyError):
            self.out.write(f'Error: node "{name}" not found\n')
            return 1
        self.out.write(f"node/{name} {'cordoned' if on else 'uncordoned'}\n")
        return 0

    def drain(self, name: str) -> int:
        """cordon + evict every pod on the node (cmd/drain.go)."""
        rc = self.cordon(name, True)
        if rc:
            return rc
        pods, _ = self.cs.pods.list()
        for pod in pods:
            if pod.spec.node_name == name:
                try:
                    self.cs.pods.delete(pod.meta.name, pod.meta.namespace)
                    self.out.write(f"pod/{pod.meta.name} evicted\n")
                except NotFoundError:
                    pass
        self.out.write(f"node/{name} drained\n")
        return 0

    def top_nodes(self) -> int:
        nodes, _ = self.cs.nodes.list()
        pods, _ = self.cs.pods.list()
        from ..scheduler.units import CPU_MILLI, MEM_MIB, pod_request_vec

        usage: dict[str, list[int]] = {}
        for p in pods:
            if p.spec.node_name:
                vec = pod_request_vec(p)
                u = usage.setdefault(p.spec.node_name, [0, 0])
                u[0] += vec[CPU_MILLI]
                u[1] += vec[MEM_MIB]
        rows = [("NAME", "CPU(requested)", "MEMORY(requested)")]
        for n in nodes:
            u = usage.get(n.meta.name, [0, 0])
            rows.append((n.meta.name, f"{u[0]}m", f"{u[1]}Mi"))
        self._print(*rows)
        return 0


def main(argv: Optional[list[str]] = None, clientset: Optional[Clientset] = None, out=None) -> int:
    # SUPPRESS so a subparser never clobbers a value parsed before the verb
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--server", default=argparse.SUPPRESS)
    common.add_argument("--token", default=argparse.SUPPRESS)
    common.add_argument("-n", "--namespace", default=argparse.SUPPRESS)
    common.add_argument("-o", "--output", default=argparse.SUPPRESS)  # ""|json|yaml|jsonpath=...

    parser = argparse.ArgumentParser(prog="kubectl-tpu", parents=[common])
    sub = parser.add_subparsers(dest="verb", required=True)

    p = sub.add_parser("get", parents=[common])
    p.add_argument("resource")
    p.add_argument("name", nargs="?")
    p.add_argument("-l", "--selector", default="")
    p = sub.add_parser("describe", parents=[common])
    p.add_argument("resource")
    p.add_argument("name")
    p = sub.add_parser("create", parents=[common])
    p.add_argument("-f", "--filename", required=True)
    p = sub.add_parser("apply", parents=[common])
    p.add_argument("-f", "--filename", required=True)
    p = sub.add_parser("delete", parents=[common])
    p.add_argument("resource")
    p.add_argument("name", nargs="?")
    p.add_argument("-l", "--selector", default="")
    p = sub.add_parser("scale", parents=[common])
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("--replicas", type=int, required=True)
    p = sub.add_parser("cordon", parents=[common])
    p.add_argument("name")
    p = sub.add_parser("uncordon", parents=[common])
    p.add_argument("name")
    p = sub.add_parser("drain", parents=[common])
    p.add_argument("name")
    p = sub.add_parser("top", parents=[common])
    p.add_argument("what", choices=["nodes", "pods"])
    p = sub.add_parser("logs", parents=[common])
    p.add_argument("name")
    p.add_argument("-c", "--container", default="")
    p.add_argument("--tail", type=int, default=0)
    p = sub.add_parser("exec", parents=[common])
    p.add_argument("name")
    p.add_argument("-c", "--container", default="")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- cmd args...")
    p = sub.add_parser("rollout", parents=[common])
    p.add_argument("action", choices=["status", "history", "undo"])
    p.add_argument("resource")  # "deployment" or "deployment/NAME"
    p.add_argument("name", nargs="?")
    p.add_argument("--to-revision", type=int, default=0)

    args = parser.parse_args(argv)
    server = getattr(args, "server", "http://127.0.0.1:8080")
    token = getattr(args, "token", None)
    namespace = getattr(args, "namespace", None)
    output = getattr(args, "output", "")
    cs = clientset or Clientset(RemoteStore(server, token=token))
    k = Kubectl(cs, out=out)
    if args.verb == "get":
        return k.get(args.resource, args.name, namespace, output, args.selector)
    if args.verb == "describe":
        return k.describe(args.resource, args.name, namespace)
    if args.verb == "create":
        return k.create(args.filename)
    if args.verb == "apply":
        return k.apply(args.filename)
    if args.verb == "delete":
        if not args.name and not args.selector:
            k.out.write("error: a name or -l selector is required\n")
            return 1
        return k.delete(args.resource, args.name, namespace, args.selector)
    if args.verb == "scale":
        return k.scale(args.resource, args.name, args.replicas, namespace)
    if args.verb == "cordon":
        return k.cordon(args.name, True)
    if args.verb == "uncordon":
        return k.cordon(args.name, False)
    if args.verb == "drain":
        return k.drain(args.name)
    if args.verb == "top":
        if args.what == "pods":
            return k.top_pods(namespace)
        return k.top_nodes()
    if args.verb == "logs":
        return k.logs(args.name, namespace, args.container, args.tail)
    if args.verb == "exec":
        cmd = list(args.command)
        if cmd and cmd[0] == "--":
            cmd = cmd[1:]  # only the FIRST separator belongs to kubectl
        if not cmd:
            k.out.write("error: command required after --\n")
            return 1
        return k.exec(args.name, cmd, namespace, args.container)
    if args.verb == "rollout":
        res = args.resource
        name = args.name
        if name is None and "/" in res:
            res, name = res.split("/", 1)
        if _resolve(res)[1] != "Deployment" or not name:
            k.out.write("error: rollout supports deployment/NAME\n")
            return 1
        if args.action == "status":
            return k.rollout_status(name, namespace)
        if args.action == "history":
            return k.rollout_history(name, namespace)
        return k.rollout_undo(name, namespace, args.to_revision)
    return 2


if __name__ == "__main__":
    sys.exit(main())
