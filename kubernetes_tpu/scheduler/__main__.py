"""kube-scheduler daemon (reference ``plugin/cmd/kube-scheduler/app/
server.go:67 Run``, leader election ``:133``).

    python -m kubernetes_tpu.scheduler --apiserver http://host:6443 \
        [--leader-elect] [--backend tpu|oracle] [--batch-interval 0.05] \
        [--policy-config-file policy.json]
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import threading

from ..daemon import install_signal_stop, remote_clientset, run_with_leader_election


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.scheduler")
    ap.add_argument("--apiserver", default=None)
    ap.add_argument("--token", default=None)
    ap.add_argument("--kubeconfig", default=None,
                    help="connection document from the kubeadm kubeconfig "
                    "phase (server + CA pin + client cert); --apiserver/"
                    "--token override its fields")
    ap.add_argument("--leader-elect", action="store_true")
    # SUPPRESS so explicit flags can be told apart from defaults when a
    # --config file is layered underneath (flag > file > default)
    ap.add_argument("--backend", choices=["tpu", "oracle"], default=argparse.SUPPRESS)
    ap.add_argument("--batch-interval", type=float, default=argparse.SUPPRESS,
                    help="seconds to coalesce pending pods before a TPU batch")
    ap.add_argument("--policy-config-file", default=argparse.SUPPRESS)
    ap.add_argument("--scheduler-name", default=argparse.SUPPRESS)
    ap.add_argument("--feature-gates", default="")
    ap.add_argument("--config", default=None,
                    help="SchedulerConfiguration YAML (componentconfig)")
    ap.add_argument("--healthz-port", type=int, default=-1,
                    help="serve /healthz + /metrics (reference :10251); "
                         "-1 = off, 0 = ephemeral")
    ap.add_argument("--trace", action="store_true",
                    help="enable wave tracing + the flight recorder; "
                         "exported at /debug/traces (Chrome trace-event "
                         "JSON) and /debug/flightrecorder on the healthz "
                         "port")
    ap.add_argument("--trace-dump-dir", default=None,
                    help="with --trace: also write each flight-recorder "
                         "dump as a JSON file under this directory")
    ap.add_argument("--timeseries", action="store_true",
                    help="scrape the metrics registry into in-process "
                         "time-series rings (served at /debug/timeseries) "
                         "and run the burn-rate SLO monitor — a breach "
                         "fires the flight recorder")
    ap.add_argument("--timeseries-interval", type=float, default=1.0,
                    help="scrape cadence in seconds (with --timeseries)")
    ap.add_argument("--telemetry-sink", default=None,
                    help="ship flight dumps + time-series deltas off-box: "
                         "an http(s):// collector URL (the apiserver's "
                         "/telemetry ingest) or a JSON-lines file path; "
                         "implies --timeseries")
    args = ap.parse_args(argv)
    from ..utils.features import SchedulerConfiguration, load_component_config

    cfg = (load_component_config(SchedulerConfiguration, args.config)
           if args.config else SchedulerConfiguration())
    # flag > config file > dataclass default
    for attr in ("scheduler_name", "backend", "batch_interval", "policy_config_file"):
        if not hasattr(args, attr):
            setattr(args, attr, getattr(cfg, attr) or (None if attr == "policy_config_file" else getattr(cfg, attr)))
    args.leader_elect = args.leader_elect or cfg.leader_elect
    if args.config and cfg.feature_gates:
        from ..utils.features import DEFAULT_FEATURE_GATES

        DEFAULT_FEATURE_GATES.set_from_map(cfg.feature_gates)
    if args.feature_gates:
        from ..utils.features import DEFAULT_FEATURE_GATES

        DEFAULT_FEATURE_GATES.set_from_string(args.feature_gates)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if not args.apiserver and not args.kubeconfig:
        ap.error("one of --apiserver or --kubeconfig is required")
    cs = remote_clientset(args.apiserver, args.token,
                          kubeconfig=args.kubeconfig)

    # health BEFORE leader election: a standby must still answer its
    # liveness probe or the supervisor kills a healthy HA peer.  The
    # metrics registry appears once the payload constructs the scheduler.
    from ..daemon import serve_health

    metrics_holder: dict = {}

    class _LazyRegistry:
        def expose(self):
            reg = metrics_holder.get("registry")
            return reg.expose() if reg is not None else "# standby\n"

    if args.trace:
        from ..utils import tracing

        tracing.enable(dump_dir=args.trace_dump_dir)
        logging.info("wave tracing enabled (flight recorder armed)")

    health = serve_health(args.healthz_port, _LazyRegistry())
    if health is not None:
        logging.info("healthz/metrics%s on :%d",
                     " + /debug/traces" if args.trace else "",
                     health.local_port)

    def run(payload_stop: threading.Event) -> None:
        from .generic_scheduler import GenericScheduler
        from .scheduler import Scheduler

        algo = GenericScheduler()
        if args.policy_config_file:
            from .policy import load_policy_file

            algo = load_policy_file(args.policy_config_file)
        backend = None
        if args.backend == "tpu":
            from ..ops import TPUBatchBackend

            backend = TPUBatchBackend(algorithm=algo)
        sched = Scheduler(cs, algorithm=algo, backend=backend,
                          scheduler_name=args.scheduler_name)
        metrics_holder["registry"] = sched.metrics.registry
        if args.timeseries or args.telemetry_sink:
            from ..daemon import enable_continuous_telemetry

            enable_continuous_telemetry(
                sched.metrics.registry,
                interval_s=args.timeseries_interval,
                sink_spec=args.telemetry_sink)
            logging.info("continuous telemetry enabled (scrape %.2fs%s)",
                         args.timeseries_interval,
                         f", sink={args.telemetry_sink}"
                         if args.telemetry_sink else "")
        sched.start(manual=False)  # threaded informers + event sink
        logging.info("scheduler running (backend=%s)", args.backend)
        while not payload_stop.is_set():
            if backend is not None:
                # continuous service mode: drain as pods arrive under the
                # min-batch/max-wait policy (batch_interval caps the
                # accumulation window); returns when payload_stop is set
                bound = sched.run_batch_loop(
                    # one full kernel segment ends the accumulation early;
                    # otherwise the window is batch_interval, matching the
                    # old fixed-interval coalescing
                    min_batch=backend.max_segment_pods,
                    max_wait=args.batch_interval, stop=payload_stop,
                    poll_interval=min(0.05, args.batch_interval))
                if bound:
                    logging.info("batch loop: %d bound", bound)
            else:
                if not sched.schedule_one(timeout=0.2, async_bind=True):
                    continue
        sched.informers.stop_all()
        sched.broadcaster.stop()
        if args.timeseries or args.telemetry_sink:
            from ..utils import telemetry, timeseries

            timeseries.disable()
            telemetry.disable()  # final drain before exit

    stop = install_signal_stop()
    try:
        run_with_leader_election(
            cs, "kube-scheduler", f"scheduler-{os.getpid()}", run, stop,
            leader_elect=args.leader_elect,
        )
    finally:
        if health is not None:
            health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
