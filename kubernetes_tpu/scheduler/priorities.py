"""Oracle scoring priorities — the fixed-point scoring spec.

Capability of the reference's default priority set
(``plugin/pkg/scheduler/algorithm/priorities/``; registration
``algorithmprovider/defaults/defaults.go:188-228``).  Scores are integers
0..10 per priority per node (``schedulerapi.MaxPriority``), combined by
integer weighted sum (``core/generic_scheduler.go:374-379``).

Where the reference computes intermediate *fractions* in float64 and
truncates (``int(fScore)``), this framework's spec replaces the float math
with 10-bit fixed point (``x*1024//y``) or direct integer division — chosen
so that for non-negative operands the result equals ``floor`` of the real
value, exactly what Go's ``int()`` truncation produces.  All intermediates
fit int32 at the 5k-node/150k-pod design scale, so the TPU kernels
(``kubernetes_tpu/ops/scores.py``) reproduce these numbers bit-for-bit.

Each priority exposes ``compute_all(pod, infos, ctx) -> list[int]``
(scores aligned with ``infos``) — the whole-node-axis shape that both the
oracle and the vectorized kernels share.
"""

from __future__ import annotations

from typing import Optional

from ..api import types as api
from ..api.selectors import matches_simple_selector
from .nodeinfo import NodeInfo
from .units import (
    CPU_MILLI,
    FIXED_POINT_ONE,
    MAX_PRIORITY,
    MEM_MIB,
    pod_nonzero_request_vec,
)
from .predicates import _pod_matches_term

PREFER_AVOID_PODS_ANNOTATION = "scheduler.alpha.kubernetes.io/preferAvoidPods"
DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1

# ImageLocality bounds, canonical MiB (reference image_locality.go:
# minImgSize 23MB, maxImgSize 1000MB).
_MIN_IMG_MIB = 23
_MAX_IMG_MIB = 1000


class PriorityContext:
    """Cluster-wide lookups for priorities: grouping objects for spread and
    the node-info map for topology."""

    def __init__(
        self,
        node_info_map: dict[str, NodeInfo],
        services: Optional[list[api.Service]] = None,
        replicasets: Optional[list[api.ReplicaSet]] = None,
        hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT,
        pvcs: Optional[dict[str, object]] = None,
        pvs: Optional[dict[str, object]] = None,
    ):
        self.node_info_map = node_info_map
        self.services = services or []
        self.replicasets = replicasets or []
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        # volume listers consumed by the predicate context ("ns/name" -> PVC,
        # name -> PV); carried here so one context object reaches both the
        # scoring and (via GenericScheduler.schedule) the filtering phase
        self.pvcs = pvcs or {}
        self.pvs = pvs or {}


# one zone-key implementation for oracle AND tensorizer (bit-parity):
from .nodeinfo import _zone_key_of as _zone_key_of_node


def _zone_key(node: Optional[api.Node]) -> str:
    """reference ``utilnode.GetZoneKey``; scoring loops read the cached
    ``NodeInfo.zone_key`` (same function) instead."""
    return _zone_key_of_node(node)


# ---------------------------------------------------------------------------
# Resource-shape priorities (least/most requested, balanced)
# ---------------------------------------------------------------------------


def _least_requested_score(requested: int, capacity: int) -> int:
    """reference least_requested.go:65 calculateUnusedScore."""
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return ((capacity - requested) * MAX_PRIORITY) // capacity


def _most_requested_score(requested: int, capacity: int) -> int:
    """reference most_requested.go:41 calculateUsedScore."""
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return (requested * MAX_PRIORITY) // capacity


class LeastRequestedPriority:
    """(capacity-requested)*10/capacity averaged over cpu+mem, on NONZERO
    requests (least_requested.go:33)."""

    name = "LeastRequestedPriority"

    def compute_all(self, pod: api.Pod, infos: list[NodeInfo], ctx: PriorityContext) -> list[int]:
        req = pod_nonzero_request_vec(pod)
        rc, rm = req.units[CPU_MILLI], req.units[MEM_MIB]
        out = []
        for info in infos:
            nz, al = info.nonzero_requested.units, info.allocatable.units
            cpu = _least_requested_score(nz[CPU_MILLI] + rc, al[CPU_MILLI])
            mem = _least_requested_score(nz[MEM_MIB] + rm, al[MEM_MIB])
            out.append((cpu + mem) // 2)
        return out


class MostRequestedPriority:
    """Bin-packing twin of LeastRequested (most_requested.go:33; the
    ClusterAutoscalerProvider default and BASELINE 'MostAllocated')."""

    name = "MostRequestedPriority"

    def compute_all(self, pod, infos, ctx) -> list[int]:
        req = pod_nonzero_request_vec(pod)
        rc, rm = req.units[CPU_MILLI], req.units[MEM_MIB]
        out = []
        for info in infos:
            nz, al = info.nonzero_requested.units, info.allocatable.units
            cpu = _most_requested_score(nz[CPU_MILLI] + rc, al[CPU_MILLI])
            mem = _most_requested_score(nz[MEM_MIB] + rm, al[MEM_MIB])
            out.append((cpu + mem) // 2)
        return out


class BalancedResourceAllocation:
    """10 - 10*|cpuFraction - memFraction| (balanced_resource_allocation.go),
    fractions in 10-bit fixed point."""

    name = "BalancedResourceAllocation"

    def compute_all(self, pod, infos, ctx) -> list[int]:
        req = pod_nonzero_request_vec(pod)
        rc, rm = req.units[CPU_MILLI], req.units[MEM_MIB]
        out = []
        for info in infos:
            nz, al = info.nonzero_requested.units, info.allocatable.units
            cpu_req = nz[CPU_MILLI] + rc
            mem_req = nz[MEM_MIB] + rm
            cpu_cap = al[CPU_MILLI]
            mem_cap = al[MEM_MIB]
            if cpu_cap == 0 or mem_cap == 0 or cpu_req >= cpu_cap or mem_req >= mem_cap:
                out.append(0)
                continue
            f_cpu = (cpu_req * FIXED_POINT_ONE) // cpu_cap
            f_mem = (mem_req * FIXED_POINT_ONE) // mem_cap
            diff = abs(f_cpu - f_mem)
            out.append((MAX_PRIORITY * FIXED_POINT_ONE - diff * MAX_PRIORITY) // FIXED_POINT_ONE)
        return out


# ---------------------------------------------------------------------------
# Spreading
# ---------------------------------------------------------------------------


class SelectorSpreadPriority:
    """Spread pods of the same service/replicaset across nodes and zones
    (selector_spreading.go:98; zoneWeighting=2/3 at :35 becomes the exact
    (node + 2*zone)/3 fixed-point blend here)."""

    name = "SelectorSpreadPriority"

    def _selectors_for_pod(self, pod: api.Pod, ctx: PriorityContext):
        sels = []
        for svc in ctx.services:
            if svc.meta.namespace == pod.meta.namespace and svc.selector:
                if matches_simple_selector(svc.selector, pod.meta.labels):
                    sels.append(("simple", svc.selector))
        for rs in ctx.replicasets:
            if rs.meta.namespace == pod.meta.namespace and not rs.selector.is_empty():
                if rs.selector.matches(pod.meta.labels):
                    sels.append(("label", rs.selector))
        return sels

    def _matches_any(self, sels, q: api.Pod) -> bool:
        for kind, sel in sels:
            if kind == "simple":
                if matches_simple_selector(sel, q.meta.labels):
                    return True
            else:
                if sel.matches(q.meta.labels):
                    return True
        return False

    def compute_all(self, pod, infos, ctx) -> list[int]:
        sels = self._selectors_for_pod(pod, ctx)
        counts = []
        zone_counts: dict[str, int] = {}
        for info in infos:
            cnt = 0
            if sels:
                for q in info.pods:
                    if q.meta.namespace == pod.meta.namespace and self._matches_any(sels, q):
                        cnt += 1
            counts.append(cnt)
            zk = info.zone_key
            if zk:
                zone_counts[zk] = zone_counts.get(zk, 0) + cnt
        max_n = max(counts, default=0)
        have_zones = len(zone_counts) != 0
        max_z = max(zone_counts.values(), default=0)
        out = []
        for info, cnt in zip(infos, counts):
            node_fp = (
                ((max_n - cnt) * MAX_PRIORITY * FIXED_POINT_ONE) // max_n
                if max_n > 0
                else MAX_PRIORITY * FIXED_POINT_ONE
            )
            total_fp = node_fp
            if have_zones:
                zk = info.zone_key
                if zk:
                    zone_fp = (
                        ((max_z - zone_counts[zk]) * MAX_PRIORITY * FIXED_POINT_ONE) // max_z
                        if max_z > 0
                        else MAX_PRIORITY * FIXED_POINT_ONE
                    )
                    # fScore*(1/3) + zoneScore*(2/3), exact in thirds
                    total_fp = (node_fp + 2 * zone_fp) // 3
            out.append(total_fp // FIXED_POINT_ONE)
        return out


# ---------------------------------------------------------------------------
# Node-preference priorities
# ---------------------------------------------------------------------------


class NodeAffinityPriority:
    """Sum of matching preferred node-affinity term weights, normalized
    10*count/max (node_affinity.go Map/Reduce)."""

    name = "NodeAffinityPriority"

    def compute_all(self, pod, infos, ctx) -> list[int]:
        aff = pod.spec.affinity
        terms = aff.node_affinity_preferred if aff else []
        counts = []
        for info in infos:
            cnt = 0
            if info.node is not None:
                for pt in terms:
                    if pt.weight > 0 and pt.preference.matches(info.node.meta.labels):
                        cnt += pt.weight
            counts.append(cnt)
        max_c = max(counts, default=0)
        if max_c == 0:
            return [0] * len(infos)
        return [(MAX_PRIORITY * c) // max_c for c in counts]


class TaintTolerationPriority:
    """Fewer intolerable PreferNoSchedule taints is better
    (taint_toleration.go; reduce is reversed-normalize)."""

    name = "TaintTolerationPriority"

    def compute_all(self, pod, infos, ctx) -> list[int]:
        counts = []
        for info in infos:
            cnt = 0
            if info.node is not None:
                for taint in info.node.spec.taints:
                    if taint.effect != api.PREFER_NO_SCHEDULE:
                        continue
                    if not any(t.tolerates(taint) for t in pod.spec.tolerations):
                        cnt += 1
            counts.append(cnt)
        max_c = max(counts, default=0)
        if max_c == 0:
            return [MAX_PRIORITY] * len(infos)
        return [(MAX_PRIORITY * (max_c - c)) // max_c for c in counts]


class NodePreferAvoidPodsPriority:
    """Weight-10000 veto for nodes annotated to avoid this pod's controller
    (node_prefer_avoid_pods.go).  The annotation value here is a
    comma-separated list of controller UIDs (the reference uses a JSON
    AvoidPods struct; capability is identical)."""

    name = "NodePreferAvoidPodsPriority"

    def compute_all(self, pod, infos, ctx) -> list[int]:
        ref = pod.meta.controller_ref()
        out = []
        for info in infos:
            if ref is None or ref.kind not in ("ReplicaSet", "ReplicationController"):
                out.append(MAX_PRIORITY)
                continue
            ann = info.node.meta.annotations.get(PREFER_AVOID_PODS_ANNOTATION, "") if info.node else ""
            avoided = ref.uid in [u.strip() for u in ann.split(",") if u.strip()]
            out.append(0 if avoided else MAX_PRIORITY)
        return out


class ImageLocalityPriority:
    """Prefer nodes that already hold the pod's images (image_locality.go),
    non-default in the reference's provider but registered."""

    name = "ImageLocalityPriority"

    def compute_all(self, pod, infos, ctx) -> list[int]:
        images = {c.image for c in pod.spec.containers if c.image}
        out = []
        for info in infos:
            total_mib = 0
            if info.node is not None:
                for img in info.node.status.images:
                    if any(n in images for n in img.get("names", [])):
                        total_mib += int(img.get("sizeBytes", 0)) // (2**20)
            if total_mib < _MIN_IMG_MIB:
                out.append(0)
            elif total_mib > _MAX_IMG_MIB:
                out.append(MAX_PRIORITY)
            else:
                out.append(((total_mib - _MIN_IMG_MIB) * MAX_PRIORITY) // (_MAX_IMG_MIB - _MIN_IMG_MIB))
        return out


class EqualPriority:
    name = "EqualPriority"

    def compute_all(self, pod, infos, ctx) -> list[int]:
        return [1] * len(infos)


# ---------------------------------------------------------------------------
# Inter-pod affinity scoring (interpod_affinity.go:119) — O(pods x terms)
# term processing into a (topologyKey, value) weight accumulator, then a
# per-node gather + min/max normalization.
# ---------------------------------------------------------------------------


class InterPodAffinityPriority:
    name = "InterPodAffinityPriority"

    def compute_all(self, pod, infos, ctx: PriorityContext) -> list[int]:
        aff = pod.spec.affinity
        # (topology_key, value) -> accumulated weight
        topo_weights: dict[tuple[str, str], int] = {}

        def add(node: Optional[api.Node], key: str, weight: int) -> None:
            if node is None or not key:
                return
            value = node.meta.labels.get(key)
            if value is None:
                return
            topo_weights[(key, value)] = topo_weights.get((key, value), 0) + weight

        # Weight accumulation walks existing pods on EVERY node in the
        # cluster (reference allNodeNames from nodeNameToInfo,
        # interpod_affinity.go:124-128); only the final per-node gather below
        # is restricted to the feasible `infos`.
        for info in ctx.node_info_map.values():
            existing_pods = (
                info.pods
                if aff and (aff.pod_affinity_preferred or aff.pod_anti_affinity_preferred)
                else info.pods_with_affinity
            )
            for existing in existing_pods:
                # incoming pod's soft terms vs existing pod
                if aff is not None:
                    for wt in aff.pod_affinity_preferred:
                        if _pod_matches_term(existing, pod, wt.term):
                            add(info.node, wt.term.topology_key, wt.weight)
                    for wt in aff.pod_anti_affinity_preferred:
                        if _pod_matches_term(existing, pod, wt.term):
                            add(info.node, wt.term.topology_key, -wt.weight)
                # symmetry: existing pod's terms vs incoming pod
                eaff = existing.spec.affinity
                if eaff is not None:
                    if ctx.hard_pod_affinity_weight > 0:
                        for term in eaff.pod_affinity_required:
                            if _pod_matches_term(pod, existing, term):
                                add(info.node, term.topology_key, ctx.hard_pod_affinity_weight)
                    for wt in eaff.pod_affinity_preferred:
                        if _pod_matches_term(pod, existing, wt.term):
                            add(info.node, wt.term.topology_key, wt.weight)
                    for wt in eaff.pod_anti_affinity_preferred:
                        if _pod_matches_term(pod, existing, wt.term):
                            add(info.node, wt.term.topology_key, -wt.weight)

        counts = []
        for info in infos:
            total = 0
            if info.node is not None:
                for (key, value), w in topo_weights.items():
                    if info.node.meta.labels.get(key) == value:
                        total += w
            counts.append(total)

        # reference min/max start at 0 (declared zero-valued floats)
        max_c = max(max(counts, default=0), 0)
        min_c = min(min(counts, default=0), 0)
        if max_c == min_c:
            return [0] * len(infos)
        return [(MAX_PRIORITY * (c - min_c)) // (max_c - min_c) for c in counts]


# ---------------------------------------------------------------------------
# Default provider set (defaults.go:188-228) with weights
# ---------------------------------------------------------------------------


def default_priorities() -> list[tuple[object, int]]:
    return [
        (SelectorSpreadPriority(), 1),
        (InterPodAffinityPriority(), 1),
        (LeastRequestedPriority(), 1),
        (BalancedResourceAllocation(), 1),
        (NodePreferAvoidPodsPriority(), 10000),
        (NodeAffinityPriority(), 1),
        (TaintTolerationPriority(), 1),
    ]


def cluster_autoscaler_priorities() -> list[tuple[object, int]]:
    """defaults.go:65-66: swap LeastRequested for MostRequested (bin-pack)."""
    out = []
    for prio, weight in default_priorities():
        if isinstance(prio, LeastRequestedPriority):
            out.append((MostRequestedPriority(), weight))
        else:
            out.append((prio, weight))
    return out


class ServiceSpreadingPriority(SelectorSpreadPriority):
    """Registered non-default priority (``defaults.go``
    ServiceSpreadingPriority): SelectorSpread restricted to SERVICE
    selectors only — the pre-SelectorSpread spreading behavior kept for
    compatibility.

    No kernel weight: not in ``ops/backend._PRIORITY_WEIGHT_KEY``, so any
    config using it schedules through the oracle path (its spread_inc
    semantics differ from SelectorSpread's, which IS kernel-mapped)."""

    # kernel: host-fallback — compat-only priority; configs using it take the all-oracle path (no _PRIORITY_WEIGHT_KEY entry)
    name = "ServiceSpreadingPriority"

    def _selectors_for_pod(self, pod: api.Pod, ctx: PriorityContext):
        return [
            ("simple", svc.selector)
            for svc in ctx.services
            if svc.meta.namespace == pod.meta.namespace and svc.selector
            and matches_simple_selector(svc.selector, pod.meta.labels)
        ]
