"""Priority preemption: make room for important pods by evicting less
important ones.

The reference has NO scheduler preemption (SURVEY.md §2.4 known-absent;
only kubelet critical-pod preemption exists, ``preemption.go:66``) but
BASELINE.json demands the modern ``DefaultPreemption`` PostFilter
capability, so this is designed fresh rather than ported:

- candidate nodes: where the pod would fit if every strictly-lower-priority
  pod were gone (a vectorizable mask — ``ops/preemption_kernel`` computes
  it over the node axis for whole failed cohorts);
- per-candidate victim selection: start from "all lower-priority pods
  evicted", then *reprieve* victims back highest-priority-first while the
  pod still fits — yielding a minimal victim set biased toward sparing
  important pods;
- node choice (deterministic spec): (1) lowest maximum victim priority,
  (2) fewest victims, (3) smallest total victim request, (4) node order.

Two execution paths share ``_evaluate_node`` (the exact per-node victim
selection), so their decisions are identical by construction:

- ``find_preemption_target``: the oracle — evaluate every node (the
  correctness reference, and the fallback when no prefilter state is
  available);
- ``find_preemption_target_fast``: evaluate only prefiltered candidates
  in ascending bound order (branch-and-bound).  The prefilter bound —
  the smallest priority level v such that evicting every pod with
  priority < v frees enough *resources* — is a true lower bound on the
  exact max-victim-priority (any feasible victim set must free enough
  resources, and resources are monotone in eviction even where affinity
  is not), so stopping once ``bound > best.max_prio`` provably never
  changes the chosen target.

Execution model: victims are deleted through the API (the disruption-aware
eviction subresource when it lands), the preemptor is requeued immediately
with its backoff reset — in this store victims vanish synchronously, so
the retry schedules into the freed space.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..api import types as api
from .nodeinfo import NodeInfo
from .predicates import (
    DEFAULT_PREDICATES,
    PredicateContext,
    compute_metadata,
    pod_fits_on_node,
)
from .units import (
    CPU_MILLI,
    GPU_COUNT,
    MEM_MIB,
    NUM_RESOURCES,
    STORAGE_MIB,
    pod_request_vec,
)


@dataclass
class PreemptionTarget:
    node_name: str
    victims: list[api.Pod]


def _fits_without(pod, meta, info: NodeInfo, removed: list[api.Pod], ctx, predicates) -> bool:
    """Feasibility of `pod` on `info` with `removed` pods taken out."""
    trial = info.clone()
    for v in removed:
        trial.remove_pod(v)
    ok, _ = pod_fits_on_node(pod, meta, trial, ctx, predicates)
    return ok


def _evaluate_node(
    pod: api.Pod, meta, name: str, info: NodeInfo, ctx, predicates
) -> Optional[tuple[tuple, PreemptionTarget]]:
    """Exact victim selection on ONE node (None if preemption there cannot
    make the pod schedulable).  Returns (rank, target); rank is the
    deterministic node-choice key."""
    lower = [q for q in info.pods if q.spec.priority < pod.spec.priority]
    if not lower:
        return None
    if not _fits_without(pod, meta, info, lower, ctx, predicates):
        return None  # even evicting everything below doesn't help
    # reprieve loop: starting from "evict all", try to re-admit victims
    # highest-priority-first; whoever cannot be re-admitted stays a victim
    victims = sorted(lower, key=lambda q: (-q.spec.priority, q.meta.key))
    for q in list(victims):
        trial = [v for v in victims if v is not q]
        if _fits_without(pod, meta, info, trial, ctx, predicates):
            victims = trial  # q reprieved
    if not victims:
        return None  # nothing actually needed evicting (shouldn't happen)
    max_prio = max(v.spec.priority for v in victims)
    total_req = [0] * NUM_RESOURCES
    for v in victims:
        vec = pod_request_vec(v)
        for r in range(NUM_RESOURCES):
            total_req[r] += vec[r]
    rank = (max_prio, len(victims), sum(total_req), name)
    return rank, PreemptionTarget(node_name=name, victims=victims)


def find_preemption_target(
    pod: api.Pod,
    node_info_map: dict[str, NodeInfo],
    predicates=None,
    pvcs=None,
    pvs=None,
) -> Optional[PreemptionTarget]:
    """The oracle: exact evaluation over EVERY node."""
    ctx = PredicateContext(node_info_map, pvcs=pvcs, pvs=pvs)
    meta = compute_metadata(pod, ctx)
    candidates: list[tuple[tuple, PreemptionTarget]] = []
    for name in sorted(n for n, i in node_info_map.items() if i.node is not None):
        got = _evaluate_node(pod, meta, name, node_info_map[name], ctx, predicates)
        if got is not None:
            candidates.append(got)
    if not candidates:
        return None
    candidates.sort(key=lambda t: t[0])
    return candidates[0][1]


def _fast_eligible(pod: api.Pod, predicates) -> bool:
    """True when every victim-DEPENDENT predicate for this preemptor is
    exactly {resources, pod count}: no host ports, no volumes, no own
    required (anti)affinity pod terms, no pinned nodeName, default
    predicate set.  All other default predicates read only node-static
    facts or the pre-eviction metadata, so the reprieve loop's
    per-trial ``pod_fits_on_node`` collapses to prefix arithmetic."""
    if predicates is not None and (
        set(predicates.keys()) != set(DEFAULT_PREDICATES.keys())
        # identity, not just names: a custom predicate registered under a
        # default key must not be silently skipped by the arithmetic path
        or any(predicates[k] is not DEFAULT_PREDICATES[k] for k in predicates)
    ):
        return False
    if pod.spec.node_name or pod.spec.volumes:
        return False
    if pod.host_ports():
        return False
    a = pod.spec.affinity
    if a is not None and (a.pod_affinity_required or a.pod_anti_affinity_required):
        return False
    return True


_CHECKED_SLOTS = (CPU_MILLI, MEM_MIB, STORAGE_MIB, GPU_COUNT)


def _greedy_rank(
    pod: api.Pod, meta, name: str, info: NodeInfo,
    vec_cache: Optional[dict] = None,
) -> Optional[tuple[tuple, list[api.Pod]]]:
    """Exact (rank, victims) for a fast-eligible preemptor — the closed
    form of ``_evaluate_node``'s reprieve loop when every victim-dependent
    check is resources+count: same victim order, same reprieve decisions,
    no NodeInfo clones.  Excludes only the node-static gate (checked once
    by the caller on the winner)."""
    p = pod.spec.priority
    lower = [q for q in info.pods if q.spec.priority < p]
    if not lower:
        return None
    req = meta.pod_request
    need = [(s, info.requested[s] + req[s] - info.allocatable[s])
            for s in _CHECKED_SLOTS if req[s] > 0]
    need_cnt = len(info.pods) + 1 - info.allocatable_pods
    if vec_cache is None:
        vecs = [pod_request_vec(q) for q in lower]
    else:
        # cohort-scoped memo: the same resident pods are re-ranked for
        # every preemptor of the cohort, and the quantity re-parse was
        # the dominant cost at fleet scale.  Entries hold the pod object
        # so id() keys stay unique for the cache's lifetime.
        vecs = []
        for q in lower:
            hit = vec_cache.get(id(q))
            if hit is None:
                hit = vec_cache[id(q)] = (q, pod_request_vec(q))
            vecs.append(hit[1])
    freed = {s: sum(v[s] for v in vecs) for s, _ in need}
    if any(freed[s] < n for s, n in need) or len(lower) < need_cnt:
        return None  # even evicting everything below doesn't free enough
    order = sorted(range(len(lower)),
                   key=lambda i: (-lower[i].spec.priority, lower[i].meta.key))
    victim = [True] * len(lower)
    nvict = len(lower)
    for i in order:
        v = vecs[i]
        if nvict - 1 >= need_cnt and all(freed[s] - v[s] >= n for s, n in need):
            victim[i] = False  # reprieved
            nvict -= 1
            for s, _ in need:
                freed[s] -= v[s]
    victims = [lower[i] for i in range(len(lower)) if victim[i]]
    if not victims:
        return None
    max_prio = max(v.spec.priority for v in victims)
    total = sum(sum(vecs[i].units) for i in range(len(lower)) if victim[i])
    return (max_prio, len(victims), total, name), victims


def find_preemption_target_fast(
    pod: api.Pod,
    node_info_map: dict[str, NodeInfo],
    candidates: list[tuple[int, str]],
    predicates=None,
    pvcs=None,
    pvs=None,
    static_cache: Optional[dict] = None,
    vec_cache: Optional[dict] = None,
    state=None,
    recheck_nodes: Optional[list] = None,
) -> Optional[PreemptionTarget]:
    """Exact selection over PREFILTERED candidates.

    ``candidates``: (bound, node_name) pairs from
    ``ops.preemption_kernel`` — bound is the resource-only lower bound on
    the node's max victim priority; the list must contain every node the
    oracle could pick (the prefilter keeps all resource-feasible nodes).

    Fast-eligible preemptors (the common template-stamped case) get exact
    ranks for every candidate from ``_greedy_rank`` prefix arithmetic and
    walk them in rank order, paying the full-predicate node-static gate
    (one clone) only until the first pass — with ``static_cache``
    memoizing that gate per node across a cohort of same-signature
    preemptors.  Everyone else gets branch-and-bound over
    ``_evaluate_node``: ascending (bound, name) order, stopping once no
    remaining bound can beat or tie the best exact criterion (1).
    Either way the chosen target equals ``find_preemption_target``'s.
    """
    ctx = PredicateContext(node_info_map, pvcs=pvcs, pvs=pvs)
    meta = compute_metadata(pod, ctx)

    if recheck_nodes:
        # earlier cohort evictions freed space on exactly these nodes —
        # the only ones that can have become feasible since the batch
        # proved this pod unschedulable.  Entries are (name, shadow_info)
        # where the shadow carries BOTH the evictions and the claims of
        # previously-granted cohort members (otherwise every preemptor
        # double-claims the same freed capacity).  A full-predicate fit
        # there means NO eviction is needed: signalled by empty victims;
        # the caller records the claim in the shadow.
        for name, info in recheck_nodes:
            if info is None or info.node is None:
                continue
            fits, _ = pod_fits_on_node(pod, meta, info, ctx, predicates)
            if fits:
                return PreemptionTarget(node_name=name, victims=[])

    if _fast_eligible(pod, predicates):
        if state is not None:
            # vectorized exact ranks over ALL nodes at once (the
            # ops/preemption_kernel greedy): rank order assembled by
            # lexsort, victims materialized only for gate-checked winners
            import numpy as np

            ok, max_prio, n_vict, total, victim = state.rank_arrays(
                meta.pod_request.units, pod.spec.priority, node_info_map)
            idx = np.flatnonzero(ok)
            # node_names is sorted, so index order IS the name tie-break
            order = idx[np.lexsort((idx, total[idx], n_vict[idx],
                                    max_prio[idx]))]
            ranked = (
                ((int(max_prio[j]), int(n_vict[j]), int(total[j]),
                  state.node_names[j]),
                 [q for c, q in enumerate(state.pp_pods[j])
                  if victim[j, c]])
                for j in order
            )
        else:
            got_all = []
            for _, name in candidates:
                info = node_info_map.get(name)
                if info is None or info.node is None:
                    continue
                got = _greedy_rank(pod, meta, name, info, vec_cache)
                if got is not None:
                    got_all.append(got)
            got_all.sort(key=lambda t: t[0])
            ranked = iter(got_all)
        for rank, victims in ranked:
            name = rank[3]
            info = node_info_map.get(name)
            if info is None or info.node is None:
                continue  # vanished mid-cohort (stale state row)
            ok = None
            if static_cache is not None:
                hit = static_cache.get(name)
                # generation-checked: a node whose pods/labels moved since
                # the gate ran re-evaluates (evictions bump the generation,
                # but the gate's resource part is re-proven by _greedy_rank,
                # and its static part only depends on the node object —
                # still, stale entries must never outlive a node UPDATE)
                if hit is not None and hit[0] == info.generation:
                    ok = hit[1]
            if ok is None:
                lower = [q for q in info.pods if q.spec.priority < pod.spec.priority]
                ok = _fits_without(pod, meta, info, lower, ctx, predicates)
                if static_cache is not None:
                    static_cache[name] = (info.generation, ok)
            if ok:
                return PreemptionTarget(node_name=name, victims=victims)
        return None

    best: Optional[tuple[tuple, PreemptionTarget]] = None
    for bound, name in sorted(candidates):
        if best is not None and bound > best[0][0]:
            break  # no remaining candidate can beat or tie criterion (1)
        info = node_info_map.get(name)
        if info is None or info.node is None:
            continue
        got = _evaluate_node(pod, meta, name, info, ctx, predicates)
        if got is not None and (best is None or got[0] < best[0]):
            best = got
    return best[1] if best else None
