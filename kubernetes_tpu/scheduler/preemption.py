"""Priority preemption: make room for important pods by evicting less
important ones.

The reference has NO scheduler preemption (SURVEY.md §2.4 known-absent;
only kubelet critical-pod preemption exists, ``preemption.go:66``) but
BASELINE.json demands the modern ``DefaultPreemption`` PostFilter
capability, so this is designed fresh rather than ported:

- candidate nodes: where the pod would fit if every strictly-lower-priority
  pod were gone (a vectorizable mask — the device helper in
  ``ops/filters.preemption_candidates`` computes it over the node axis);
- per-candidate victim selection: start from "all lower-priority pods
  evicted", then *reprieve* victims back highest-priority-first while the
  pod still fits — yielding a minimal victim set biased toward sparing
  important pods;
- node choice (deterministic spec): (1) lowest maximum victim priority,
  (2) fewest victims, (3) smallest total victim request, (4) node order.

Execution model: victims are deleted through the API (the disruption-aware
eviction subresource when it lands), the preemptor is requeued immediately
with its backoff reset — in this store victims vanish synchronously, so
the retry schedules into the freed space.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..api import types as api
from .nodeinfo import NodeInfo
from .predicates import PredicateContext, compute_metadata, pod_fits_on_node
from .units import NUM_RESOURCES, pod_request_vec


@dataclass
class PreemptionTarget:
    node_name: str
    victims: list[api.Pod]


def _fits_without(pod, meta, info: NodeInfo, removed: list[api.Pod], ctx, predicates) -> bool:
    """Feasibility of `pod` on `info` with `removed` pods taken out."""
    trial = info.clone()
    for v in removed:
        trial.remove_pod(v)
    ok, _ = pod_fits_on_node(pod, meta, trial, ctx, predicates)
    return ok


def find_preemption_target(
    pod: api.Pod,
    node_info_map: dict[str, NodeInfo],
    predicates=None,
    pvcs=None,
    pvs=None,
) -> Optional[PreemptionTarget]:
    ctx = PredicateContext(node_info_map, pvcs=pvcs, pvs=pvs)
    meta = compute_metadata(pod, ctx)
    candidates: list[tuple[tuple, PreemptionTarget]] = []

    for name in sorted(n for n, i in node_info_map.items() if i.node is not None):
        info = node_info_map[name]
        lower = [q for q in info.pods if q.spec.priority < pod.spec.priority]
        if not lower:
            continue
        if not _fits_without(pod, meta, info, lower, ctx, predicates):
            continue  # even evicting everything below doesn't help
        # reprieve loop: starting from "evict all", try to re-admit victims
        # highest-priority-first; whoever cannot be re-admitted stays a victim
        victims = sorted(lower, key=lambda q: (-q.spec.priority, q.meta.key))
        for q in list(victims):
            trial = [v for v in victims if v is not q]
            if _fits_without(pod, meta, info, trial, ctx, predicates):
                victims = trial  # q reprieved
        if not victims:
            continue  # nothing actually needed evicting (shouldn't happen)
        max_prio = max(v.spec.priority for v in victims)
        total_req = [0] * NUM_RESOURCES
        for v in victims:
            vec = pod_request_vec(v)
            for r in range(NUM_RESOURCES):
                total_req[r] += vec[r]
        rank = (max_prio, len(victims), sum(total_req), name)
        candidates.append((rank, PreemptionTarget(node_name=name, victims=victims)))

    if not candidates:
        return None
    candidates.sort(key=lambda t: t[0])
    return candidates[0][1]
