"""Canonical fixed-point units — the scheduler's numeric spec.

The reference computes scheduling math on arbitrary-precision Quantities
(int64 milli-values) on the CPU.  A TPU kernel computes in int32/float32
lanes.  To make "identical bindings" a *testable bit-exact property* instead
of an approximation, this framework defines ONE canonical fixed-point
representation used by BOTH the CPU oracle and the TPU kernels:

- cpu               → integer millicores          (``Quantity.milli_value``)
- memory            → integer MiB, rounded up
- ephemeral-storage → integer MiB, rounded up
- nvidia.com/gpu    → integer count
- pods              → integer count

All scores are integers 0..10 per priority function (the reference's
``MaxPriority``, ``plugin/pkg/scheduler/api/types.go``), combined by integer
weighted sum; fractional intermediates use 10-bit fixed point (x*1024//y).
Every operation fits comfortably in int32 — exactly what the TPU VPU
computes natively — so oracle scores and kernel scores are equal by
construction, not by tolerance.

Rounding deviates from the reference only at sub-MiB granularity (the
reference divides raw bytes); that is this framework's documented spec.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..api import lazy as lazy_mod
from ..api import types as api
from ..api.quantity import Quantity

# Resource-vector slot layout, shared by the oracle (NodeInfo) and the
# tensorizer (models/snapshot).  Order matters: it is the R axis of every
# [N, R] / [P, R] array on device.
CPU_MILLI = 0
MEM_MIB = 1
STORAGE_MIB = 2
GPU_COUNT = 3
NUM_RESOURCES = 4

RESOURCE_SLOTS = {
    api.CPU: CPU_MILLI,
    api.MEMORY: MEM_MIB,
    api.EPHEMERAL_STORAGE: STORAGE_MIB,
    api.GPU: GPU_COUNT,
}

MAX_PRIORITY = 10  # reference schedulerapi.MaxPriority
FIXED_POINT_ONE = 1024  # 10-bit fixed-point scale for fractions

# Priorities score against *non-zero* requests: containers with no request
# count as 100 millicores / 200 MiB (reference
# ``algorithm/priorities/util/non_zero.go:29-43`` DefaultMilliCpuRequest /
# DefaultMemoryRequest = 200MB; canonicalized here to MiB).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEM_MIB_REQUEST = 200

MIB = 2**20


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _away_from_zero_div(num: int, den: int) -> int:
    """Quantity.value()/milli_value() rounding: positives round away
    from zero; negatives take divmod's floor, which is ALSO away from
    zero — any reimplementation (e.g. in a kernel) must match both."""
    q, r = divmod(num, den)
    if r != 0 and num > 0:
        q += 1
    return q


def _slot_units_cached(slot: int, f) -> int:
    if slot == CPU_MILLI:
        return _away_from_zero_div(f.numerator * 1000, f.denominator)
    if slot in (MEM_MIB, STORAGE_MIB):
        return _ceil_div(f.numerator, f.denominator * MIB)
    return _away_from_zero_div(f.numerator, f.denominator)


_slot_units_memo: dict = {}


def quantity_to_slot_units(slot: int, q: Quantity) -> int:
    """Canonicalize one Quantity into its slot's integer unit.  Memoized:
    resource strings come from a tiny vocabulary ("100m", "128Mi", ...)
    but this runs for every container of every pod admitted to every
    tensor build — Fraction arithmetic is the oracle's hottest scalar op."""
    f = q.fraction
    key = (slot, f.numerator, f.denominator)
    got = _slot_units_memo.get(key)
    if got is None:
        if len(_slot_units_memo) > 65536:
            _slot_units_memo.clear()
        got = _slot_units_memo[key] = _slot_units_cached(slot, f)
    return got


@dataclass
class ResourceVec:
    """Fixed-size integer resource vector (one row of the [*, R] tensors)."""

    units: list[int]

    def __init__(self, units: "list[int] | None" = None):
        self.units = list(units) if units is not None else [0] * NUM_RESOURCES

    @classmethod
    def from_resource_list(cls, rl: dict[str, Quantity]) -> "ResourceVec":
        v = cls()
        for name, q in rl.items():
            slot = RESOURCE_SLOTS.get(name)
            if slot is not None:
                v.units[slot] += quantity_to_slot_units(slot, q)
        return v

    def add(self, other: "ResourceVec") -> None:
        for i in range(NUM_RESOURCES):
            self.units[i] += other.units[i]

    def sub(self, other: "ResourceVec") -> None:
        for i in range(NUM_RESOURCES):
            self.units[i] -= other.units[i]

    def copy(self) -> "ResourceVec":
        return ResourceVec(self.units)

    def __getitem__(self, slot: int) -> int:
        return self.units[slot]

    def __eq__(self, other) -> bool:
        return isinstance(other, ResourceVec) and self.units == other.units

    def __repr__(self) -> str:
        return f"ResourceVec(cpu_m={self.units[0]}, mem_mib={self.units[1]}, storage_mib={self.units[2]}, gpu={self.units[3]})"


# per-CONTAINER request parse memo for the raw (wire-dict) fast path:
# keyed by the container's sorted request items, so a template-stamped
# fleet parses each distinct container shape once.  Content-keyed, never
# pinned per pod — the per-pod vector cache A/B (below) showed per-pod
# derived objects cost more in cyclic-GC walks than they save.
_raw_container_memo: dict = {}


def _raw_container_units(requests: dict) -> tuple[tuple, tuple]:
    """(request units, nonzero units) for one container's raw requests
    dict, in canonical slot order; nonzero applies the per-container
    cpu/mem defaults exactly like ``pod_nonzero_request_vec``."""
    key = tuple(sorted(requests.items()))
    got = _raw_container_memo.get(key)
    if got is None:
        if len(_raw_container_memo) > 65536:
            _raw_container_memo.clear()
        units = [0] * NUM_RESOURCES
        for name, q in requests.items():
            slot = RESOURCE_SLOTS.get(name)
            if slot is not None:
                units[slot] += quantity_to_slot_units(slot, Quantity(q))
        nz = list(units)
        if nz[CPU_MILLI] == 0:
            nz[CPU_MILLI] = DEFAULT_MILLI_CPU_REQUEST
        if nz[MEM_MIB] == 0:
            nz[MEM_MIB] = DEFAULT_MEM_MIB_REQUEST
        got = _raw_container_memo[key] = (tuple(units), tuple(nz))
    return got


def raw_request_units(spec: dict) -> tuple[list[int], list[int]]:
    """Summed (request, nonzero-request) unit vectors straight from a raw
    pod-spec dict — the column-batch / lazy-pod parse that must equal
    ``pod_request_vec``/``pod_nonzero_request_vec`` of the decoded pod
    (test_lazy pins the equivalence)."""
    req = [0] * NUM_RESOURCES
    nz = [0] * NUM_RESOURCES
    for c in spec.get("containers") or []:
        u, un = _raw_container_units(
            (c.get("resources") or {}).get("requests") or {})
        for i in range(NUM_RESOURCES):
            req[i] += u[i]
            nz[i] += un[i]
    return req, nz


def pod_request_vec(pod: api.Pod) -> ResourceVec:
    """Raw summed container requests in canonical units (predicate side;
    reference ``predicates.GetResourceRequest``).

    Deliberately NOT cached on the pod object: an A/B at the north preset
    measured per-pod vector caching at -20% throughput — pinning two
    extra objects per pod (~1.2M at 150k pods) makes every cyclic-GC pass
    slower, which outweighs the ~4us/call rebuild it saves.  The slot
    conversion underneath is already memoized.  Lazy pods whose spec is
    still undecoded parse straight from the wire dict through the
    content-memoized container table — no Container objects built."""
    spec_raw = lazy_mod.undecoded_spec(pod)
    if spec_raw is not None:
        return ResourceVec(raw_request_units(spec_raw)[0])
    v = ResourceVec()
    for c in pod.spec.containers:
        v.add(ResourceVec.from_resource_list(c.resources.requests))
    return v


def pod_nonzero_request_vec(pod: api.Pod) -> ResourceVec:
    """Summed container requests with per-container cpu/mem defaults for
    empty requests (priority side; reference ``priorities/util/non_zero.go``)."""
    spec_raw = lazy_mod.undecoded_spec(pod)
    if spec_raw is not None:
        return ResourceVec(raw_request_units(spec_raw)[1])
    v = ResourceVec()
    for c in pod.spec.containers:
        cv = ResourceVec.from_resource_list(c.resources.requests)
        if cv.units[CPU_MILLI] == 0:
            cv.units[CPU_MILLI] = DEFAULT_MILLI_CPU_REQUEST
        if cv.units[MEM_MIB] == 0:
            cv.units[MEM_MIB] = DEFAULT_MEM_MIB_REQUEST
        v.add(cv)
    return v


def node_allocatable_vec(node: api.Node) -> ResourceVec:
    return ResourceVec.from_resource_list(node.status.allocatable)


def node_allocatable_pods(node: api.Node) -> int:
    q = node.status.allocatable.get(api.PODS)
    return q.value() if q is not None else 110
