"""NodeInfo and the scheduler cache — the assume/bind protocol.

Capability of the reference's ``plugin/pkg/scheduler/schedulercache``
(``node_info.go:34 NodeInfo``, ``cache.go:38 New``, ``AssumePod :109``,
``FinishBinding :130``, ``ForgetPod :154``, expiry loop ``:346-379``):

- ``NodeInfo`` aggregates everything predicates/priorities read per node in
  canonical fixed-point units (this is the struct the tensorizer flattens
  into the [N, R] device arrays);
- the cache lets scheduling run AHEAD of binding: ``assume_pod`` commits
  resources locally before the (async) bind lands; confirmed by the watch
  (``add_pod``), or expired after a TTL if the binding never shows up
  (SURVEY.md P9 — the 1-deep pipeline the TPU batch path widens to
  batch-depth);
- generation counters give copy-on-write snapshots (``cache.go:79``): a
  snapshot refresh only touches nodes whose generation moved, which is also
  what makes *incremental* host→device tensor updates possible.

Time is injected (``clock``) so the assume-expiry state machine is
deterministic under test, like the reference's ``util/clock``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..api import lazy as lazy_mod
from ..api import types as api
from .units import (
    ResourceVec,
    node_allocatable_pods,
    node_allocatable_vec,
    pod_nonzero_request_vec,
    pod_request_vec,
)


def _zone_key_of(node) -> str:
    """reference ``utilnode.GetZoneKey`` (region+zone label pair), cached on
    the NodeInfo because scoring reads it for every node on every pod."""
    if node is None:
        return ""
    labels = node.meta.labels
    region = labels.get(api.REGION_LABEL, "")
    zone = labels.get(api.ZONE_LABEL, "")
    if not region and not zone:
        return ""
    return f"{region}:{zone}"


def pod_has_affinity(pod: api.Pod) -> bool:
    spec_raw = lazy_mod.undecoded_spec(pod)
    if spec_raw is not None:
        return lazy_mod.raw_has_affinity(spec_raw)
    a = pod.spec.affinity
    return a is not None and bool(
        a.pod_affinity_required
        or a.pod_affinity_preferred
        or a.pod_anti_affinity_required
        or a.pod_anti_affinity_preferred
    )


def _containers_equal(a: api.Pod, b: api.Pod) -> bool:
    """Container-list equality without forcing a decode when both sides
    still hold their wire payloads (the assume→watch-confirm hot path:
    the confirmed object differs from the assumed one only by nodeName
    and resourceVersion, so the raw subtrees compare equal by value)."""
    ra = lazy_mod.undecoded_spec(a)
    rb = lazy_mod.undecoded_spec(b)
    if ra is not None and rb is not None:
        return (ra.get("containers") or []) == (rb.get("containers") or [])
    return a.spec.containers == b.spec.containers


class NodeInfo:
    """Aggregated per-node scheduling state (``node_info.go:34``)."""

    def __init__(self, node: Optional[api.Node] = None):
        self.node: Optional[api.Node] = node
        self.pods: list[api.Pod] = []
        self.pods_with_affinity: list[api.Pod] = []
        self.requested = ResourceVec()
        self.nonzero_requested = ResourceVec()
        self.allocatable = node_allocatable_vec(node) if node else ResourceVec()
        self.allocatable_pods = node_allocatable_pods(node) if node else 0
        self.used_ports: set[tuple[str, int]] = set()
        self.generation = 0
        self.zone_key = _zone_key_of(node)  # cached region:zone label pair

    # -- node object -------------------------------------------------------
    def set_node(self, node: api.Node) -> None:
        self.node = node
        self.allocatable = node_allocatable_vec(node)
        self.allocatable_pods = node_allocatable_pods(node)
        self.zone_key = _zone_key_of(node)
        self.generation += 1

    def remove_node(self) -> None:
        self.node = None
        self.zone_key = ""
        self.generation += 1

    # -- pod aggregation ---------------------------------------------------
    def add_pod(self, pod: api.Pod) -> None:
        self.add_pod_counted(pod, pod_request_vec(pod), pod_nonzero_request_vec(pod))

    def add_pod_counted(self, pod: api.Pod, req_vec, nz_vec) -> None:
        """``add_pod`` with PRECOMPUTED request vectors: the batch backend
        already holds per-signature vectors, and re-parsing quantities for
        every placed pod dominated the host-side apply cost at 150k pods.
        The vectors MUST equal ``pod_request_vec(pod)`` /
        ``pod_nonzero_request_vec(pod)`` — ``remove_pod`` re-derives them
        for the subtraction."""
        self.pods.append(pod)
        if pod_has_affinity(pod):
            self.pods_with_affinity.append(pod)
        self.requested.add(req_vec)
        self.nonzero_requested.add(nz_vec)
        for port in pod.host_ports():
            self.used_ports.add(port)
        self.generation += 1

    def replace_pod(self, old_pod: api.Pod, new_pod: api.Pod) -> bool:
        """Swap one resident pod object for a content-equivalent newer
        version WITHOUT re-aggregating (same requests/ports/affinity —
        the caller asserts equivalence, e.g. via pod_signature_key).
        The assume→watch-confirm swap is the hot caller: the confirmed
        API object differs from the assumed one only by nodeName and
        resourceVersion, and the remove+add path's port-set rebuild is
        O(pods-on-node) for nothing."""
        key = new_pod.meta.key
        for i, p in enumerate(self.pods):
            if p is old_pod or p.meta.key == key:
                self.pods[i] = new_pod
                break
        else:
            return False
        for i, p in enumerate(self.pods_with_affinity):
            if p is old_pod or p.meta.key == key:
                self.pods_with_affinity[i] = new_pod
                break
        self.generation += 1
        return True

    def remove_pod(self, pod: api.Pod) -> bool:
        for i, p in enumerate(self.pods):
            if p.meta.key == pod.meta.key:
                del self.pods[i]
                break
        else:
            return False
        self.pods_with_affinity = [
            p for p in self.pods_with_affinity if p.meta.key != pod.meta.key
        ]
        self.requested.sub(pod_request_vec(pod))
        self.nonzero_requested.sub(pod_nonzero_request_vec(pod))
        # Rebuild ports from the remaining pods: pods force-bound via
        # spec.nodeName bypass predicates, so two residents CAN hold the
        # same host port — a plain discard would free it too early.
        self.used_ports = {p for q in self.pods for p in q.host_ports()}
        self.generation += 1
        return True

    def clone(self) -> "NodeInfo":
        c = NodeInfo()
        c.node = self.node
        c.zone_key = self.zone_key
        c.pods = list(self.pods)
        c.pods_with_affinity = list(self.pods_with_affinity)
        c.requested = self.requested.copy()
        c.nonzero_requested = self.nonzero_requested.copy()
        c.allocatable = self.allocatable.copy()
        c.allocatable_pods = self.allocatable_pods
        c.used_ports = set(self.used_ports)
        c.generation = self.generation
        return c

    @property
    def memory_pressure(self) -> bool:
        if self.node is None:
            return False
        c = self.node.status.condition(api.NODE_MEMORY_PRESSURE)
        return c is not None and c.status == "True"

    @property
    def disk_pressure(self) -> bool:
        if self.node is None:
            return False
        c = self.node.status.condition(api.NODE_DISK_PRESSURE)
        return c is not None and c.status == "True"


class SchedulerCache:
    """Assume/confirm/expire pod cache (``schedulercache/cache.go``)."""

    def __init__(self, ttl: float = 30.0, clock: Callable[[], float] = time.monotonic):
        self._mu = threading.RLock()
        self._nodes: dict[str, NodeInfo] = {}
        # pod key -> (pod, node_name, state); state ∈ {assumed, bound}
        self._pod_states: dict[str, tuple[api.Pod, str, str]] = {}
        self._assume_deadlines: dict[str, float] = {}
        self._ttl = ttl
        self._clock = clock

    # -- nodes -------------------------------------------------------------
    def add_node(self, node: api.Node) -> None:
        with self._mu:
            info = self._nodes.get(node.meta.name)
            if info is None:
                info = NodeInfo()
                self._nodes[node.meta.name] = info
            info.set_node(node)

    def update_node(self, node: api.Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        with self._mu:
            info = self._nodes.get(name)
            if info is None:
                return
            if info.pods:
                info.remove_node()  # keep pod aggregation until pods go away
            else:
                del self._nodes[name]

    # -- assume / confirm / forget ----------------------------------------
    def assume_pod(self, pod: api.Pod, node_name: str) -> None:
        self.assume_many([(pod, node_name)])

    def assume_many(self, pairs: list) -> None:
        """Batch assume under ONE lock acquisition + deadline read — the
        TPU path lands 150k assumptions at once and per-pod locking is
        measurable at that scale.  Same semantics as assume_pod per pair.

        Entries are (pod, node_name) or (pod, node_name, req_vec, nz_vec);
        the 4-tuple form carries the batch backend's per-signature request
        vectors so the aggregation skips the per-pod quantity parse (they
        MUST equal ``pod_request_vec(pod)``/``pod_nonzero_request_vec``,
        the ``add_pod_counted`` contract)."""
        deadline = self._clock() + self._ttl
        with self._mu:
            for entry in pairs:
                pod, node_name = entry[0], entry[1]
                key = pod.meta.key
                if key in self._pod_states:
                    raise ValueError(f"pod {key} already assumed/added")
                info = self._node_info(node_name)
                if len(entry) >= 4 and entry[2] is not None:
                    info.add_pod_counted(pod, entry[2], entry[3])
                else:
                    info.add_pod(pod)
                self._pod_states[key] = (pod, node_name, "assumed")
                self._assume_deadlines[key] = deadline

    def finish_binding(self, pod_key: str) -> None:
        """Binding RPC issued; start the expiry clock (``cache.go:130``)."""
        self.finish_binding_many([pod_key])

    def finish_binding_many(self, pod_keys: list) -> None:
        deadline = self._clock() + self._ttl
        with self._mu:
            for key in pod_keys:
                self._assume_deadlines[key] = deadline

    def forget_pod(self, pod: api.Pod) -> None:
        """Bind failed: roll the assumption back (``cache.go:154``)."""
        with self._mu:
            key = pod.meta.key
            st = self._pod_states.get(key)
            if st is None or st[2] != "assumed":
                return
            _, node_name, _ = st
            self._nodes[node_name].remove_pod(pod)
            del self._pod_states[key]
            self._assume_deadlines.pop(key, None)

    def confirm_many(self, entries: list) -> list:
        """Columnar wave confirm (ISSUE 6): one lock hold for a whole
        bind-confirm frame.  ``entries`` are ``(key, node_name, prev_rev,
        new_pod)`` straight off the frame's identity/node/prev-revision
        columns.  An entry is confirmed — assumed object swapped for the
        API truth WITHOUT re-aggregation — when the cache holds a
        matching assumption AND the frame's ``prev_rev`` equals the
        assumed object's resourceVersion: by CAS semantics the bind txn
        then mutated exactly nodeName/resourceVersion, so the per-pod
        containers/affinity equality check collapses to one integer
        compare per column entry.  Anything the columnar fence rejects
        (no assumption, different node, an intervening write) is returned
        UNTOUCHED for the caller's per-pod fallback path."""
        leftover: list = []
        with self._mu:
            for entry in entries:
                # (key, node_name, prev_rev, new, *caller_context) — extra
                # fields ride through untouched for the fallback router
                key, node_name, prev_rev, new = entry[:4]
                st = self._pod_states.get(key)
                if st is None or st[2] != "assumed" or st[1] != node_name:
                    leftover.append(entry)
                    continue
                assumed = st[0]
                if (prev_rev < 0
                        or lazy_mod.resource_version_of(assumed) != prev_rev
                        or not self._nodes[node_name].replace_pod(assumed, new)):
                    leftover.append(entry)
                    continue
                self._pod_states[key] = (new, node_name, "bound")
                self._assume_deadlines.pop(key, None)
        return leftover

    def add_pod(self, pod: api.Pod) -> None:
        """Watch-confirmed bound pod.  Confirms a matching assumption, or
        (re)inserts after expiry/restart."""
        with self._mu:
            key = pod.meta.key
            st = self._pod_states.get(key)
            if st is not None and st[2] == "assumed":
                assumed_pod, node_name, _ = st
                if node_name == pod.spec.node_name:
                    # confirm: swap the assumed object for the API truth.
                    # Every NodeInfo aggregate derives from
                    # spec.containers (requests, ports) and the affinity
                    # flag; when those are unchanged (the normal bind —
                    # only nodeName/resourceVersion moved) swap identity
                    # without re-aggregating.  A concurrent spec change
                    # falls back to remove+add.
                    info = self._nodes[node_name]
                    if not (_containers_equal(assumed_pod, pod)
                            and pod_has_affinity(assumed_pod) == pod_has_affinity(pod)
                            and info.replace_pod(assumed_pod, pod)):
                        info.remove_pod(assumed_pod)
                        info.add_pod(pod)
                    self._pod_states[key] = (pod, node_name, "bound")
                    self._assume_deadlines.pop(key, None)
                    return
                # bound somewhere else than assumed: trust the API
                self._nodes[node_name].remove_pod(assumed_pod)
                self._pod_states.pop(key, None)
                self._assume_deadlines.pop(key, None)
            if not pod.spec.node_name:
                return
            self._node_info(pod.spec.node_name).add_pod(pod)
            self._pod_states[key] = (pod, pod.spec.node_name, "bound")

    def update_pod(self, old: api.Pod, new: api.Pod) -> None:
        with self._mu:
            self.remove_pod(old)
            if new.spec.node_name:
                self.add_pod(new)

    def remove_pod(self, pod: api.Pod) -> None:
        with self._mu:
            key = pod.meta.key
            st = self._pod_states.pop(key, None)
            self._assume_deadlines.pop(key, None)
            if st is None:
                return
            cached_pod, node_name, _ = st
            info = self._nodes.get(node_name)
            if info is not None:
                info.remove_pod(cached_pod)
                if info.node is None and not info.pods:
                    del self._nodes[node_name]

    def is_assumed(self, pod_key: str) -> bool:
        with self._mu:
            st = self._pod_states.get(pod_key)
            return st is not None and st[2] == "assumed"

    def cleanup_expired(self) -> list[str]:
        """Expire assumed pods whose binding never confirmed
        (``cache.go:346-379``); returns expired keys."""
        with self._mu:
            now = self._clock()
            expired = [
                k
                for k, deadline in self._assume_deadlines.items()
                if deadline <= now and self._pod_states.get(k, (None, None, ""))[2] == "assumed"
            ]
            for key in expired:
                pod, node_name, _ = self._pod_states[key]
                self._nodes[node_name].remove_pod(pod)
                del self._pod_states[key]
                del self._assume_deadlines[key]
            return expired

    # -- snapshot ----------------------------------------------------------
    def _node_info(self, name: str) -> NodeInfo:
        info = self._nodes.get(name)
        if info is None:
            info = NodeInfo()
            self._nodes[name] = info
        return info

    def snapshot_into(self, out: dict[str, NodeInfo]) -> None:
        """Generation-checked copy-on-write snapshot refresh
        (``cache.go:79 UpdateNodeNameToInfoMap``): only clone nodes whose
        generation moved; drop vanished nodes."""
        with self._mu:
            for name, info in self._nodes.items():
                cur = out.get(name)
                if cur is None or cur.generation != info.generation:
                    out[name] = info.clone()
            for name in list(out.keys()):
                if name not in self._nodes:
                    del out[name]

    def node_names(self) -> list[str]:
        with self._mu:
            return [n for n, i in self._nodes.items() if i.node is not None]

    def pod_count(self) -> int:
        with self._mu:
            return len(self._pod_states)
