"""Scheduler extender: out-of-process scheduling hooks over HTTP.

Capability of the reference's ``SchedulerExtender``
(``core/extender.go:40 HTTPExtender``, ``Filter :100``, ``Prioritize :157``,
``Bind :199``) — the reference's only sanctioned out-of-process scheduling
seam (SURVEY.md terminology table).  JSON-over-HTTP webhooks:

- Filter: POST {pod, nodeNames} -> {nodeNames, failedNodes{name: reason}}
- Prioritize: POST {pod, nodeNames} -> [{host, score}]  (weighted in)
- Bind (optional): POST {podNamespace, podName, node} -> {error}

An extender that declares ``bind`` takes over the binding commit for pods
it filtered — the scheduler calls it instead of the Binding subresource.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Optional

from ..api import types as api


class ExtenderError(Exception):
    pass


class HTTPExtender:
    def __init__(
        self,
        url_prefix: str,
        filter_verb: str = "",
        prioritize_verb: str = "",
        bind_verb: str = "",
        weight: int = 1,
        timeout: float = 5.0,
    ):
        self.url_prefix = url_prefix.rstrip("/")
        self.filter_verb = filter_verb
        self.prioritize_verb = prioritize_verb
        self.bind_verb = bind_verb
        self.weight = weight
        self.timeout = timeout

    @classmethod
    def from_config(cls, spec: dict) -> "HTTPExtender":
        return cls(
            url_prefix=spec["urlPrefix"],
            filter_verb=spec.get("filterVerb", ""),
            prioritize_verb=spec.get("prioritizeVerb", ""),
            bind_verb=spec.get("bindVerb", ""),
            weight=int(spec.get("weight", 1)),
            timeout=float(spec.get("httpTimeout", 5.0)),
        )

    def _post(self, verb: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self.url_prefix}/{verb}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001
            raise ExtenderError(f"extender {self.url_prefix}/{verb}: {e}") from e

    # -- the three hooks (GenericScheduler calls these) --------------------
    def filter(self, pod: api.Pod, node_names: list[str]) -> tuple[list[str], dict[str, list[str]]]:
        if not self.filter_verb:
            return node_names, {}
        out = self._post(self.filter_verb, {"pod": pod.to_dict(), "nodeNames": node_names})
        failed = {name: [reason] for name, reason in (out.get("failedNodes") or {}).items()}
        return list(out.get("nodeNames") or []), failed

    def prioritize(self, pod: api.Pod, node_names: list[str]) -> list[int]:
        if not self.prioritize_verb:
            return [0] * len(node_names)
        out = self._post(self.prioritize_verb, {"pod": pod.to_dict(), "nodeNames": node_names})
        by_host = {e["host"]: int(e["score"]) for e in out}
        return [self.weight * by_host.get(n, 0) for n in node_names]

    def is_binder(self) -> bool:
        return bool(self.bind_verb)

    def bind(self, binding: api.Binding) -> None:
        out = self._post(self.bind_verb, binding.to_dict())
        if out.get("error"):
            raise ExtenderError(out["error"])
