"""Scheduler policy configuration + algorithm providers.

Capability of the reference's ``schedulerapi.Policy``
(``plugin/pkg/scheduler/api/types.go:38``, validation in ``api/validation``,
``--policy-config-file``) and named algorithm providers
(``algorithmprovider/defaults/defaults.go:63,118,188``,
``--algorithm-provider``): select predicates and priorities by name and
weight from JSON/dict config, with extender declarations.

The TPU backend consumes the same config: any selection the kernel can
express runs on device, anything else falls back to the oracle — so policy
files are honored identically on both paths.
"""

from __future__ import annotations

import json
from typing import Optional

from .generic_scheduler import GenericScheduler
from .predicates import DEFAULT_PREDICATES
from .priorities import (
    BalancedResourceAllocation,
    EqualPriority,
    ImageLocalityPriority,
    InterPodAffinityPriority,
    LeastRequestedPriority,
    MostRequestedPriority,
    NodeAffinityPriority,
    NodePreferAvoidPodsPriority,
    SelectorSpreadPriority,
    ServiceSpreadingPriority,
    TaintTolerationPriority,
    cluster_autoscaler_priorities,
    default_priorities,
)

# name -> predicate fn (the RegisterFitPredicate registry, factory/plugins.go)
PREDICATE_REGISTRY = dict(DEFAULT_PREDICATES)

# name -> priority class (RegisterPriorityFunction2)
PRIORITY_REGISTRY = {
    "LeastRequestedPriority": LeastRequestedPriority,
    "MostRequestedPriority": MostRequestedPriority,
    "BalancedResourceAllocation": BalancedResourceAllocation,
    "SelectorSpreadPriority": SelectorSpreadPriority,
    "NodeAffinityPriority": NodeAffinityPriority,
    "TaintTolerationPriority": TaintTolerationPriority,
    "NodePreferAvoidPodsPriority": NodePreferAvoidPodsPriority,
    "InterPodAffinityPriority": InterPodAffinityPriority,
    "ImageLocalityPriority": ImageLocalityPriority,
    "ServiceSpreadingPriority": ServiceSpreadingPriority,
    "EqualPriority": EqualPriority,
}


class PolicyError(ValueError):
    pass


def algorithm_from_provider(name: str = "DefaultProvider") -> GenericScheduler:
    """Named provider sets (defaults.go:63): DefaultProvider and
    ClusterAutoscalerProvider (LeastRequested swapped for MostRequested)."""
    if name == "DefaultProvider":
        return GenericScheduler(priorities=default_priorities())
    if name == "ClusterAutoscalerProvider":
        return GenericScheduler(priorities=cluster_autoscaler_priorities())
    raise PolicyError(f"unknown algorithm provider {name!r}")


def algorithm_from_policy(policy: "dict | str", extenders: Optional[list] = None) -> GenericScheduler:
    """Build a scheduler algorithm from a Policy dict / JSON string:

    {"predicates": [{"name": "GeneralPredicates"}, ...],
     "priorities": [{"name": "LeastRequestedPriority", "weight": 1}, ...],
     "extenders": [{"urlPrefix": ..., "filterVerb": ..., ...}]}

    Empty lists mean "none" (reference semantics: an explicit empty policy
    disables that phase); omit the key to get the defaults.
    """
    if isinstance(policy, str):
        policy = json.loads(policy)

    if "predicates" in policy:
        predicates = {}
        for spec in policy["predicates"]:
            name = spec["name"]
            arg = spec.get("argument") or {}
            if "labelsPresence" in arg:
                # CheckNodeLabelPresence-style factory (api/types.go:
                # PredicateArgument.LabelsPresence)
                from .predicates import make_check_node_label_presence

                lp = arg["labelsPresence"]
                predicates[name] = make_check_node_label_presence(
                    list(lp.get("labels") or []), bool(lp.get("presence", True)))
                continue
            if "serviceAffinity" in arg:
                from .predicates import make_check_service_affinity

                predicates[name] = make_check_service_affinity(
                    list(arg["serviceAffinity"].get("labels") or []))
                continue
            fn = PREDICATE_REGISTRY.get(name)
            if fn is None:
                raise PolicyError(f"unknown predicate {name!r}")
            predicates[name] = fn
    else:
        predicates = dict(DEFAULT_PREDICATES)

    if "priorities" in policy:
        priorities = []
        for spec in policy["priorities"]:
            name = spec["name"]
            cls = PRIORITY_REGISTRY.get(name)
            if cls is None:
                raise PolicyError(f"unknown priority {name!r}")
            weight = int(spec.get("weight", 1))
            if weight <= 0:
                raise PolicyError(f"priority {name!r} weight must be positive")
            priorities.append((cls(), weight))
    else:
        priorities = default_priorities()

    ext = list(extenders or [])
    for spec in policy.get("extenders", []):
        from .extender import HTTPExtender

        ext.append(HTTPExtender.from_config(spec))

    return GenericScheduler(predicates=predicates, priorities=priorities, extenders=ext)


def load_policy_file(path: str) -> GenericScheduler:
    with open(path) as f:
        return algorithm_from_policy(f.read())
