"""Oracle filter predicates — the feasibility spec.

Capability of the reference's default predicate set
(``plugin/pkg/scheduler/algorithm/predicates/predicates.go``; registration
``algorithmprovider/defaults/defaults.go:118-186``).  This module is the
sequential CPU *oracle*: the behavioral specification that the TPU
feasibility masks (built in ``kubernetes_tpu/models/snapshot.py`` and
evaluated by ``kubernetes_tpu/ops/batch_kernel.py`` /
``ops/pallas_kernel.py``) must reproduce bit-for-bit on the canonical
fixed-point units.

Each predicate: ``fn(pod, meta, node_info, ctx) -> (ok, reasons)`` where
``meta`` is per-pod precomputation shared across all nodes (reference
``predicates/metadata.go``) and ``ctx`` exposes cluster-wide lookups (all
pods, node-by-name) like the reference's ``PodAffinityChecker`` listers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api import types as api
from ..api.selectors import matches_simple_selector
from .nodeinfo import NodeInfo
from .units import CPU_MILLI, GPU_COUNT, MEM_MIB, STORAGE_MIB, ResourceVec, pod_request_vec

# Failure reasons (predicate name -> human string), mirroring the
# reference's typed PredicateFailureReasons.
INSUFFICIENT_CPU = "Insufficient cpu"
INSUFFICIENT_MEMORY = "Insufficient memory"
INSUFFICIENT_STORAGE = "Insufficient ephemeral-storage"
INSUFFICIENT_GPU = "Insufficient nvidia.com/gpu"
INSUFFICIENT_PODS = "Too many pods"
NODE_NOT_MATCH_HOST = "node(s) didn't match the requested hostname"
PORT_CONFLICT = "node(s) didn't have free ports"
SELECTOR_MISMATCH = "node(s) didn't match node selector"
TAINT_NOT_TOLERATED = "node(s) had taints that the pod didn't tolerate"
MEMORY_PRESSURE = "node(s) had memory pressure"
DISK_PRESSURE = "node(s) had disk pressure"
DISK_CONFLICT = "node(s) had no available disk"
MAX_VOLUME_COUNT = "node(s) exceed max volume count"
VOLUME_ZONE_CONFLICT = "node(s) had volume zone conflict"
VOLUME_NODE_CONFLICT = "node(s) didn't match PersistentVolume's node affinity"
UNBOUND_PVC = "pod has unbound/missing PersistentVolumeClaim"
AFFINITY_NOT_MATCH = "node(s) didn't satisfy inter-pod (anti)affinity"
NODE_UNSCHEDULABLE = "node(s) were unschedulable"
NODE_NOT_READY = "node(s) were not ready"


@dataclass
class MatchingAntiAffinityTerm:
    """An existing pod's required anti-affinity term that selects the pod
    being scheduled (the symmetry set, reference
    ``getMatchingAntiAffinityTerms`` ``predicates.go:1065,1120``)."""

    term: api.PodAffinityTerm
    owner_node_labels: dict[str, str]


@dataclass
class PredicateMetadata:
    """Per-pod precomputation shared across all nodes
    (``predicates/metadata.go``).  Cheap host-side work done once per pod;
    the batch tensorizer computes the same things as [P, ...] arrays."""

    pod_request: ResourceVec = field(default_factory=ResourceVec)
    is_best_effort: bool = False
    host_ports: list[tuple[str, int]] = field(default_factory=list)
    matching_anti_affinity_terms: list[MatchingAntiAffinityTerm] = field(default_factory=list)
    # The pod's OWN required (anti)affinity terms, collapsed to topology
    # VALUE SETS per term (computed once per pod; the per-node check then
    # costs O(1) set lookups instead of an all-pods scan — the value-set
    # form of predicates.go:1181's per-node scan, bit-identical because
    # _same_topology is exactly "both nodes carry the key with equal
    # values").  None = pod carries no such terms.
    own_affinity_values: "list[tuple[str, set, bool, bool]] | None" = None
    # [(topology_key, matching_values, matching_pod_exists, self_match)]
    own_anti_affinity_values: "list[tuple[str, set]] | None" = None
    # [(topology_key, forbidden_values)]
    # Symmetry set collapsed the same way: key -> owner-node values where
    # co-location is forbidden; sym_always_fails = a symmetry term with no
    # topology key (forbids every node, as the term list form does)
    sym_forbidden: "dict[str, set] | None" = None
    sym_always_fails: bool = False


class PredicateContext:
    """Cluster-wide lookups for cross-node predicates (affinity).

    The pod lists are memoized: one Schedule() call evaluates N nodes
    against the same snapshot, and rebuilding a 150k-pod list per node
    would dominate the filter phase (the reference avoids this with
    predicate metadata, ``predicates/metadata.go``)."""

    def __init__(
        self,
        node_info_map: dict[str, NodeInfo],
        pvcs: Optional[dict[str, object]] = None,
        pvs: Optional[dict[str, object]] = None,
        services: Optional[list] = None,
    ):
        self.node_info_map = node_info_map
        # "ns/name" -> PersistentVolumeClaim; name -> PersistentVolume
        # (the reference threads pvcLister/pvLister into the volume
        # predicates via ConfigFactory, factory.go:120)
        self.pvcs = pvcs or {}
        self.pvs = pvs or {}
        # Services (CheckServiceAffinity reads the serviceLister the same
        # way, predicates.go:821)
        self.services = services or []
        self._all_pods: Optional[list[tuple[api.Pod, NodeInfo]]] = None
        self._all_pods_with_affinity: Optional[list[tuple[api.Pod, NodeInfo]]] = None

    def bound_pv_for(self, pod: api.Pod, vol: api.Volume):
        """Resolve a pod volume's PVC reference to its bound PV.
        Returns (pv, ok): ok=False means missing/unbound claim (the
        reference fails scheduling on lookup errors, predicates.go:430)."""
        pvc = self.pvcs.get(f"{pod.meta.namespace}/{vol.pvc_name}")
        if pvc is None or not pvc.volume_name:
            return None, False
        pv = self.pvs.get(pvc.volume_name)
        if pv is None:
            return None, False
        return pv, True

    def all_pods_with_affinity(self) -> list[tuple[api.Pod, NodeInfo]]:
        if self._all_pods_with_affinity is None:
            self._all_pods_with_affinity = [
                (p, info)
                for info in self.node_info_map.values()
                for p in info.pods_with_affinity
            ]
        return self._all_pods_with_affinity

    def all_pods(self) -> list[tuple[api.Pod, NodeInfo]]:
        if self._all_pods is None:
            self._all_pods = [
                (p, info) for info in self.node_info_map.values() for p in info.pods
            ]
        return self._all_pods

    def node_labels(self, node_name: str) -> dict[str, str]:
        info = self.node_info_map.get(node_name)
        if info is None or info.node is None:
            return {}
        return info.node.meta.labels


def compute_metadata(pod: api.Pod, ctx: PredicateContext) -> PredicateMetadata:
    meta = PredicateMetadata(
        pod_request=pod_request_vec(pod),
        is_best_effort=pod.qos_class() == api.BEST_EFFORT,
        host_ports=pod.host_ports(),
    )
    # Symmetry set: every existing pod whose required anti-affinity selects
    # this pod forbids co-location within its term's topology domain.
    for existing, info in ctx.all_pods_with_affinity():
        aff = existing.spec.affinity
        if aff is None or not aff.pod_anti_affinity_required:
            continue
        node_labels = info.node.meta.labels if info.node else {}
        for term in aff.pod_anti_affinity_required:
            if _pod_matches_term(pod, existing, term):
                meta.matching_anti_affinity_terms.append(
                    MatchingAntiAffinityTerm(term=term, owner_node_labels=node_labels)
                )
    if meta.matching_anti_affinity_terms:
        meta.sym_forbidden = {}
        for mt in meta.matching_anti_affinity_terms:
            key = mt.term.topology_key
            if not key:
                meta.sym_always_fails = True
                continue
            if key in mt.owner_node_labels:
                meta.sym_forbidden.setdefault(key, set()).add(mt.owner_node_labels[key])

    # The pod's own required terms, collapsed to per-term topology value
    # sets in ONE pass over the cluster (instead of one pass per node)
    aff = pod.spec.affinity
    if aff is not None and (aff.pod_affinity_required or aff.pod_anti_affinity_required):
        all_pods = ctx.all_pods()
        if aff.pod_affinity_required:
            meta.own_affinity_values = []
            for term in aff.pod_affinity_required:
                values: set = set()
                exists = False
                for existing, existing_info in all_pods:
                    if not _pod_matches_term(existing, pod, term):
                        continue
                    exists = True
                    labels = existing_info.node.meta.labels if existing_info.node else {}
                    if term.topology_key in labels:
                        values.add(labels[term.topology_key])
                meta.own_affinity_values.append(
                    (term.topology_key, values, exists, _pod_matches_term(pod, pod, term))
                )
        if aff.pod_anti_affinity_required:
            meta.own_anti_affinity_values = []
            for term in aff.pod_anti_affinity_required:
                values = set()
                for existing, existing_info in all_pods:
                    if not _pod_matches_term(existing, pod, term):
                        continue
                    labels = existing_info.node.meta.labels if existing_info.node else {}
                    if term.topology_key in labels:
                        values.add(labels[term.topology_key])
                meta.own_anti_affinity_values.append((term.topology_key, values))
    return meta


def _pod_matches_term(candidate: api.Pod, term_owner: api.Pod, term: api.PodAffinityTerm) -> bool:
    """Does ``candidate`` fall in the term's namespace+selector scope?
    (reference ``priorityutil.PodMatchesTermsNamespaceAndSelector``)"""
    namespaces = term.namespaces or [term_owner.meta.namespace]
    if candidate.meta.namespace not in namespaces:
        return False
    if term.selector is None:
        return False
    return term.selector.matches(candidate.meta.labels)


def _same_topology(labels_a: dict[str, str], labels_b: dict[str, str], key: str) -> bool:
    """reference ``priorityutil.NodesHaveSameTopologyKey``: both nodes carry
    the key and the values are equal."""
    if not key:
        return False
    return key in labels_a and key in labels_b and labels_a[key] == labels_b[key]


# ---------------------------------------------------------------------------
# GeneralPredicates (predicates.go:900): resources + host + ports + selector
# ---------------------------------------------------------------------------


def pod_fits_resources(pod, meta: PredicateMetadata, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """reference ``PodFitsResources`` (:556): requested + pod <= allocatable
    per resource, plus the pod-count dimension."""
    reasons = []
    if len(info.pods) + 1 > info.allocatable_pods:
        reasons.append(INSUFFICIENT_PODS)
    req = meta.pod_request
    checks = (
        (CPU_MILLI, INSUFFICIENT_CPU),
        (MEM_MIB, INSUFFICIENT_MEMORY),
        (STORAGE_MIB, INSUFFICIENT_STORAGE),
        (GPU_COUNT, INSUFFICIENT_GPU),
    )
    for slot, reason in checks:
        if req[slot] > 0 and info.requested[slot] + req[slot] > info.allocatable[slot]:
            reasons.append(reason)
    return (not reasons), reasons


def pod_fits_host(pod, meta, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """reference ``PodFitsHost`` (:698)."""
    if not pod.spec.node_name:
        return True, []
    ok = info.node is not None and pod.spec.node_name == info.node.meta.name
    return ok, ([] if ok else [NODE_NOT_MATCH_HOST])


def pod_fits_host_ports(pod, meta: PredicateMetadata, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """reference ``PodFitsHostPorts`` (:859)."""
    for port in meta.host_ports:
        if port in info.used_ports:
            return False, [PORT_CONFLICT]
    return True, []


def pod_matches_node_selector(pod, meta, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """reference ``PodMatchNodeSelector`` (:686) =
    ``podMatchesNodeLabels``: spec.nodeSelector AND required node affinity."""
    if info.node is None:
        return False, [SELECTOR_MISMATCH]
    labels = info.node.meta.labels
    if pod.spec.node_selector and not matches_simple_selector(pod.spec.node_selector, labels):
        return False, [SELECTOR_MISMATCH]
    aff = pod.spec.affinity
    if aff is not None and aff.node_affinity_required is not None:
        # nil terms list matches nothing is handled by NodeSelector.matches
        if not aff.node_affinity_required.matches(labels):
            return False, [SELECTOR_MISMATCH]
    return True, []


def general_predicates(pod, meta, info, ctx) -> tuple[bool, list[str]]:
    reasons: list[str] = []
    for fn in (pod_fits_resources, pod_fits_host, pod_fits_host_ports, pod_matches_node_selector):
        ok, r = fn(pod, meta, info, ctx)
        reasons.extend(r)
    return (not reasons), reasons


# ---------------------------------------------------------------------------
# Taints / node conditions
# ---------------------------------------------------------------------------


def pod_tolerates_node_taints(pod, meta, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """reference ``PodToleratesNodeTaints`` (:1241): only NoSchedule and
    NoExecute taints matter; every such taint must be tolerated."""
    if info.node is None:
        return True, []
    for taint in info.node.spec.taints:
        if taint.effect not in (api.NO_SCHEDULE, api.NO_EXECUTE):
            continue
        if not any(t.tolerates(taint) for t in pod.spec.tolerations):
            return False, [TAINT_NOT_TOLERATED]
    return True, []


def check_node_memory_pressure(pod, meta: PredicateMetadata, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """reference ``CheckNodeMemoryPressurePredicate`` (:1274): only
    BestEffort pods are blocked by memory pressure."""
    if not meta.is_best_effort:
        return True, []
    if info.memory_pressure:
        return False, [MEMORY_PRESSURE]
    return True, []


def check_node_disk_pressure(pod, meta, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """reference ``CheckNodeDiskPressurePredicate`` (:1296): blocks all pods."""
    if info.disk_pressure:
        return False, [DISK_PRESSURE]
    return True, []


def check_node_schedulable(pod, meta, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """spec.unschedulable gate (reference enforces this in the node lister
    filter, ``factory.go``'s scheduled-node predicate; kept explicit here)."""
    if info.node is not None and info.node.spec.unschedulable:
        return False, [NODE_UNSCHEDULABLE]
    return True, []


def check_node_condition(pod, meta, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """Ready-condition gate: the reference's scheduler node lister excludes
    nodes whose Ready condition is not True (``factory.go``
    getNodeConditionPredicate) — without it, pods land on dead nodes and
    ping-pong through eviction."""
    if info.node is None:
        return False, [NODE_NOT_READY]
    ready = info.node.status.condition(api.NODE_READY)
    if ready is not None and ready.status != "True":
        return False, [NODE_NOT_READY]
    return True, []


# ---------------------------------------------------------------------------
# Volumes
# ---------------------------------------------------------------------------

# Disk kinds that allow co-location when every reference is read-only
# (reference NoDiskConflict: GCE PD and ISCSI allow all-read-only sharing;
# EBS and RBD never share — predicates.go:121-183).
_READONLY_SHARED_KINDS = {"gce-pd", "iscsi"}

VOLUME_COUNT_LIMITS = {
    "aws-ebs": 39,  # DefaultMaxEBSVolumes
    "gce-pd": 16,  # DefaultMaxGCEPDVolumes
    "azure-disk": 16,
}


def no_disk_conflict(pod, meta, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    for vol in pod.spec.volumes:
        if not vol.disk_id:
            continue
        for existing in info.pods:
            for evol in existing.spec.volumes:
                if evol.disk_id != vol.disk_id or evol.disk_kind != vol.disk_kind:
                    continue
                if vol.disk_kind in _READONLY_SHARED_KINDS and vol.read_only and evol.read_only:
                    continue
                return False, [DISK_CONFLICT]
    return True, []


def max_volume_count(pod, meta, info: NodeInfo, ctx) -> tuple[bool, list[str]]:
    """reference ``MaxPDVolumeCountChecker`` (:215): per attachable-disk
    kind, distinct volumes already on the node plus the pod's new ones must
    not exceed the kind's limit."""
    for kind, limit in VOLUME_COUNT_LIMITS.items():
        pod_vols = {v.disk_id for v in pod.spec.volumes if v.disk_kind == kind and v.disk_id}
        if not pod_vols:
            continue
        node_vols = set()
        for existing in info.pods:
            for evol in existing.spec.volumes:
                if evol.disk_kind == kind and evol.disk_id:
                    node_vols.add(evol.disk_id)
        if len(node_vols | pod_vols) > limit:
            return False, [MAX_VOLUME_COUNT]
    return True, []


def no_volume_zone_conflict(pod, meta, info: NodeInfo, ctx: PredicateContext) -> tuple[bool, list[str]]:
    """reference ``VolumeZoneChecker.predicate`` (predicates.go:402): a pod
    referencing a PVC bound to a zone-labelled PV may only land on nodes in
    that zone; missing/unbound claims fail scheduling outright."""
    vols = [v for v in pod.spec.volumes if v.pvc_name]
    if not vols:
        return True, []
    if info.node is None:
        return False, [VOLUME_ZONE_CONFLICT]
    node_zone = info.node.meta.labels.get(api.ZONE_LABEL, "")
    for vol in vols:
        pv, ok = ctx.bound_pv_for(pod, vol)
        if not ok:
            return False, [UNBOUND_PVC]
        if pv.zone and pv.zone != node_zone:
            return False, [VOLUME_ZONE_CONFLICT]
    return True, []


def no_volume_node_conflict(pod, meta, info: NodeInfo, ctx: PredicateContext) -> tuple[bool, list[str]]:
    """reference ``VolumeNodeChecker.predicate`` (predicates.go:1323): a PV
    carrying node affinity (local volumes) pins its pods to matching nodes.
    Unlike the zone check, unresolvable claims are skipped here — the zone
    predicate already reports them (mirrors the reference's split where the
    node checker tolerates nil PVs)."""
    vols = [v for v in pod.spec.volumes if v.pvc_name]
    if not vols:
        return True, []
    if info.node is None:
        return False, [VOLUME_NODE_CONFLICT]
    labels = info.node.meta.labels
    for vol in vols:
        pv, ok = ctx.bound_pv_for(pod, vol)
        if not ok:
            continue
        if pv.node_affinity is not None and not pv.node_affinity.matches(labels):
            return False, [VOLUME_NODE_CONFLICT]
    return True, []


# ---------------------------------------------------------------------------
# Inter-pod affinity / anti-affinity (the reference's hot spot,
# predicates.go:982 MatchInterPodAffinity)
# ---------------------------------------------------------------------------


def match_inter_pod_affinity(pod, meta: PredicateMetadata, info: NodeInfo, ctx: PredicateContext) -> tuple[bool, list[str]]:
    if meta is None:
        # probe callers without precomputation get the real thing — the
        # scan branches below must never run against missing symmetry data
        meta = compute_metadata(pod, ctx)
    if info.node is None:
        return False, [AFFINITY_NOT_MATCH]
    node_labels = info.node.meta.labels

    # 1. Symmetry: existing pods' required anti-affinity must not be broken
    #    (satisfiesExistingPodsAntiAffinity, predicates.go:1146) — value-set
    #    form when precomputed, term-list scan otherwise
    if meta is not None and meta.sym_forbidden is not None:
        if meta.sym_always_fails:
            return False, [AFFINITY_NOT_MATCH]
        for key, values in meta.sym_forbidden.items():
            if key in node_labels and node_labels[key] in values:
                return False, [AFFINITY_NOT_MATCH]
    else:
        for mt in meta.matching_anti_affinity_terms:
            if not mt.term.topology_key:
                return False, [AFFINITY_NOT_MATCH]
            if _same_topology(node_labels, mt.owner_node_labels, mt.term.topology_key):
                return False, [AFFINITY_NOT_MATCH]

    aff = pod.spec.affinity
    if aff is None or (not aff.pod_affinity_required and not aff.pod_anti_affinity_required):
        return True, []

    # 2+3. The pod's own required terms (satisfiesPodsAffinityAntiAffinity,
    # predicates.go:1181) over the per-pod precomputed value sets: a term is
    # satisfied iff this node's topology value is in the term's matching
    # set (affinity) / out of it (anti-affinity); the first-pod rule
    # (predicates.go:1196-1216) rides the precomputed exists/self flags.
    if meta is not None and (
        meta.own_affinity_values is not None or meta.own_anti_affinity_values is not None
    ):
        for key, values, exists, self_match in meta.own_affinity_values or ():
            if not key:
                return False, [AFFINITY_NOT_MATCH]
            if node_labels.get(key) in values and key in node_labels:
                continue
            if exists:
                return False, [AFFINITY_NOT_MATCH]
            if not self_match:
                return False, [AFFINITY_NOT_MATCH]
        for key, values in meta.own_anti_affinity_values or ():
            if not key:
                return False, [AFFINITY_NOT_MATCH]
            if key in node_labels and node_labels.get(key) in values:
                return False, [AFFINITY_NOT_MATCH]
        return True, []

    # direct per-node scan (reached only with a hand-built meta lacking
    # the value sets, e.g. external predicate callers)
    all_pods = None  # lazily fetched
    for term in aff.pod_affinity_required:
        if not term.topology_key:
            return False, [AFFINITY_NOT_MATCH]
        if all_pods is None:
            all_pods = ctx.all_pods()
        term_matches = False
        matching_pod_exists = False
        for existing, existing_info in all_pods:
            if not _pod_matches_term(existing, pod, term):
                continue
            matching_pod_exists = True
            existing_labels = existing_info.node.meta.labels if existing_info.node else {}
            if _same_topology(node_labels, existing_labels, term.topology_key):
                term_matches = True
                break
        if not term_matches:
            # First-pod rule (predicates.go:1196-1216): if no pod anywhere
            # matches the term but the pod matches its own term, disregard.
            if matching_pod_exists:
                return False, [AFFINITY_NOT_MATCH]
            if not _pod_matches_term(pod, pod, term):
                return False, [AFFINITY_NOT_MATCH]

    for term in aff.pod_anti_affinity_required:
        if not term.topology_key:
            return False, [AFFINITY_NOT_MATCH]
        if all_pods is None:
            all_pods = ctx.all_pods()
        for existing, existing_info in all_pods:
            if not _pod_matches_term(existing, pod, term):
                continue
            existing_labels = existing_info.node.meta.labels if existing_info.node else {}
            if _same_topology(node_labels, existing_labels, term.topology_key):
                return False, [AFFINITY_NOT_MATCH]

    return True, []


# ---------------------------------------------------------------------------
# Registry — the default predicate set, in a fixed evaluation order
# (order affects only failure reasons, not feasibility).
# ---------------------------------------------------------------------------

PredicateFn = Callable[[api.Pod, PredicateMetadata, NodeInfo, PredicateContext], tuple[bool, list[str]]]

def make_check_node_label_presence(labels: list, presence: bool) -> PredicateFn:
    """``CheckNodeLabelPresence`` factory (predicates.go:737): with
    presence=True every listed label must EXIST on the node; with
    presence=False none may (value-agnostic — used to steer off/onto
    labeled pools).

    No kernel mask: policy-file-only predicate, and any config whose
    predicate set differs from DEFAULT_PREDICATES already takes the
    all-oracle path (``ops/backend._config_supported``)."""
    # kernel: host-fallback — policy-only; non-default predicate configs run all-oracle (backend._config_supported)

    def check_node_label_presence(pod, meta, info: NodeInfo, ctx):
        node_labels = info.node.meta.labels if info.node else {}
        for label in labels:
            if (label in node_labels) != presence:
                want = "present" if presence else "absent"
                return False, [f"node label {label!r} must be {want}"]
        return True, []

    return check_node_label_presence


def make_check_service_affinity(labels: list) -> PredicateFn:
    """``CheckServiceAffinity`` factory (predicates.go:821): pods of one
    Service co-locate on nodes sharing the same VALUES for the given
    label set — the first scheduled pod of a service pins those values
    (e.g. all of service S in one region).

    No kernel mask: the pinned values depend on which pod of the service
    lands first, a cross-pod dynamic the batch tensorizer does not model;
    non-default predicate configs run all-oracle anyway
    (``ops/backend._config_supported``)."""
    # kernel: host-fallback — first-pod-pins-values dynamic not tensorized; non-default configs run all-oracle

    def _pinned_values(pod, ctx) -> dict:
        """Node-independent: the label values this pod must match —
        explicit nodeSelector first, else inherited from the first
        resident pod of the pod's services.  Memoized on ctx (one
        Schedule call evaluates N nodes; the resident-pod scan must not
        run N times)."""
        cache = getattr(ctx, "_svc_affinity_want", None)
        if cache is None:
            cache = ctx._svc_affinity_want = {}
        hit = cache.get(id(pod))
        if hit is not None:
            return hit
        want: dict = {}
        for label in labels:
            if pod.spec.node_selector and label in pod.spec.node_selector:
                want[label] = pod.spec.node_selector[label]
        missing = [label for label in labels if label not in want]
        if missing:
            selectors = [
                svc.selector for svc in ctx.services
                if svc.selector and svc.meta.namespace == pod.meta.namespace
                and all(pod.meta.labels.get(k) == v for k, v in svc.selector.items())
            ]
            if selectors:
                for other, other_info in ctx.all_pods():
                    if other.meta.namespace != pod.meta.namespace:
                        continue
                    if not any(
                        all(other.meta.labels.get(k) == v for k, v in sel.items())
                        for sel in selectors
                    ):
                        continue
                    other_labels = (other_info.node.meta.labels
                                    if other_info.node else {})
                    for label in missing:
                        if label in other_labels:
                            want.setdefault(label, other_labels[label])
                    break  # first service pod pins the values
        cache[id(pod)] = want
        return want

    def check_service_affinity(pod, meta, info: NodeInfo, ctx):
        node_labels = info.node.meta.labels if info.node else {}
        for label, value in _pinned_values(pod, ctx).items():
            if node_labels.get(label) != value:
                return False, [
                    f"service affinity: node label {label!r} must be {value!r}"]
        return True, []

    return check_service_affinity


DEFAULT_PREDICATES: dict[str, PredicateFn] = {
    "CheckNodeSchedulable": check_node_schedulable,
    "CheckNodeCondition": check_node_condition,
    "NoDiskConflict": no_disk_conflict,
    "MaxVolumeCount": max_volume_count,
    "NoVolumeZoneConflict": no_volume_zone_conflict,
    "NoVolumeNodeConflict": no_volume_node_conflict,
    "GeneralPredicates": general_predicates,
    "PodToleratesNodeTaints": pod_tolerates_node_taints,
    "CheckNodeMemoryPressure": check_node_memory_pressure,
    "CheckNodeDiskPressure": check_node_disk_pressure,
    "MatchInterPodAffinity": match_inter_pod_affinity,
}


def pod_fits_on_node(
    pod: api.Pod,
    meta: PredicateMetadata,
    info: NodeInfo,
    ctx: PredicateContext,
    predicates: Optional[dict[str, PredicateFn]] = None,
) -> tuple[bool, list[str]]:
    """Run every predicate (``podFitsOnNode``, ``core/generic_scheduler.go:234``)
    — all of them, collecting every failure reason, like the reference."""
    reasons: list[str] = []
    for fn in (predicates or DEFAULT_PREDICATES).values():
        ok, r = fn(pod, meta, info, ctx)
        if not ok:
            reasons.extend(r)
    return (not reasons), reasons


_ECACHE_MISS = object()


def _post_cache_stages(pod, meta, info, ctx, has_disk_vols, has_pvc_vols,
                       has_own_aff) -> Optional[str]:
    """The cross-node stages (never cached): volumes + inter-pod affinity."""
    if has_disk_vols:
        ok, r = no_disk_conflict(pod, meta, info, ctx)
        if ok:
            ok, r = max_volume_count(pod, meta, info, ctx)
        if not ok:
            return r[0]
    if has_pvc_vols:
        ok, r = no_volume_zone_conflict(pod, meta, info, ctx)
        if ok:
            ok, r = no_volume_node_conflict(pod, meta, info, ctx)
        if not ok:
            return r[0]
    if has_own_aff or meta.matching_anti_affinity_terms:
        ok, r = match_inter_pod_affinity(pod, meta, info, ctx)
        if not ok:
            return r[0]
    return None


def fast_fit_nodes(
    pod: api.Pod,
    meta: PredicateMetadata,
    node_names: list,
    node_info_map: dict,
    ctx: PredicateContext,
    sig_key: Optional[str] = None,
) -> tuple[list[str], dict[str, list[str]]]:
    """The DEFAULT predicate set fused into one inline pass per node.

    SURVEY §7.1/§2.12: hot paths must not be interpreted-Python *dispatch*
    loops — 11 predicate function calls per node per pod is exactly that.
    This staged form produces IDENTICAL feasibility (every stage is the
    same arithmetic as its predicate function, in the same order); the
    only divergence is that an infeasible node reports its FIRST failing
    stage's reason rather than every failing predicate's — reasons feed
    only the failure-event message.  Custom predicate configs keep the
    full per-predicate loop.

    Pod-invariant work is hoisted: toleration checks memoize on the
    node's taint tuple, stage flags are plain attribute reads, and the
    volume/port/selector stages are skipped entirely for pods that carry
    none (the common case).

    With ``sig_key``, the equivalence-cache analogue engages (reference
    ``core/equivalence_cache.go:55``): each NodeInfo carries its OWN
    ``(generation, {signature: verdict})`` memo of the NODE-LOCAL
    predicate prefix — conditions, taints, resources, host/ports/
    selector — whose inputs are fully covered by the signature and the
    node's generation counter (add/remove_pod and set_node bump it; the
    dict is replaced whenever the generation moves, the reference's
    per-node invalidation).  Living ON the NodeInfo makes the cache
    lineage-correct by construction: the backend's speculative clones
    and a deleted-then-recreated node are different objects with
    different caches.  The cross-node stages (volumes, inter-pod
    affinity) are re-evaluated every time, exactly the split the
    reference enforces by invalidating those predicates on any cluster
    pod event."""
    feasible: list[str] = []
    failures: dict[str, list[str]] = {}

    req = meta.pod_request.units
    req_cpu, req_mem, req_sto, req_gpu = (
        req[CPU_MILLI], req[MEM_MIB], req[STORAGE_MIB], req[GPU_COUNT],
    )
    best_effort = meta.is_best_effort
    host_ports = meta.host_ports
    want_host = pod.spec.node_name
    node_selector = pod.spec.node_selector
    aff = pod.spec.affinity
    node_aff = aff.node_affinity_required if aff is not None else None
    has_disk_vols = any(v.disk_id for v in pod.spec.volumes)
    has_pvc_vols = any(v.pvc_name for v in pod.spec.volumes)
    tolerations = pod.spec.tolerations
    tol_memo: dict[tuple, bool] = {}
    has_own_aff = (
        meta.sym_forbidden is not None
        or meta.own_affinity_values is not None
        or meta.own_anti_affinity_values is not None
    )
    # the cross-node tail is skipped wholesale for plain pods — one spare
    # function call per node per pod is measurable at cluster scale
    needs_tail = (
        has_disk_vols or has_pvc_vols or has_own_aff
        or bool(meta.matching_anti_affinity_terms)
    )

    for name in node_names:
        info = node_info_map[name]
        node = info.node
        node_cache = None
        if sig_key is not None:
            node_cache = getattr(info, "_pred_cache", None)
            if node_cache is None or node_cache[0] != info.generation:
                node_cache = (info.generation, {})
                info._pred_cache = node_cache
            hit = node_cache[1].get(sig_key, _ECACHE_MISS)
            if hit is not _ECACHE_MISS:
                why = hit
                if why is None and needs_tail:
                    why = _post_cache_stages(
                        pod, meta, info, ctx, has_disk_vols, has_pvc_vols,
                        has_own_aff,
                    )
                if why is None:
                    feasible.append(name)
                else:
                    failures[name] = [why]
                continue
        why = None
        if node is None:
            why = NODE_NOT_READY
        elif node.spec.unschedulable:
            why = NODE_UNSCHEDULABLE
        else:
            ready = node.status.condition(api.NODE_READY)
            if ready is not None and ready.status != "True":
                why = NODE_NOT_READY
        if why is None and info.disk_pressure:
            why = DISK_PRESSURE
        if why is None and best_effort and info.memory_pressure:
            why = MEMORY_PRESSURE
        if why is None:
            taints = node.spec.taints
            if taints:
                tkey = tuple(
                    (t.key, t.value, t.effect) for t in taints
                    if t.effect in (api.NO_SCHEDULE, api.NO_EXECUTE)
                )
                if tkey:
                    ok = tol_memo.get(tkey)
                    if ok is None:
                        ok = all(
                            any(tol.tolerates(t) for tol in tolerations)
                            for t in taints
                            if t.effect in (api.NO_SCHEDULE, api.NO_EXECUTE)
                        )
                        tol_memo[tkey] = ok
                    if not ok:
                        why = TAINT_NOT_TOLERATED
        if why is None:
            # PodFitsResources (:556) + pod count
            alloc = info.allocatable.units
            used = info.requested.units
            if len(info.pods) + 1 > info.allocatable_pods:
                why = INSUFFICIENT_PODS
            elif req_cpu > 0 and used[CPU_MILLI] + req_cpu > alloc[CPU_MILLI]:
                why = INSUFFICIENT_CPU
            elif req_mem > 0 and used[MEM_MIB] + req_mem > alloc[MEM_MIB]:
                why = INSUFFICIENT_MEMORY
            elif req_sto > 0 and used[STORAGE_MIB] + req_sto > alloc[STORAGE_MIB]:
                why = INSUFFICIENT_STORAGE
            elif req_gpu > 0 and used[GPU_COUNT] + req_gpu > alloc[GPU_COUNT]:
                why = INSUFFICIENT_GPU
        if why is None and want_host and want_host != node.meta.name:
            why = NODE_NOT_MATCH_HOST
        if why is None and host_ports:
            for port in host_ports:
                if port in info.used_ports:
                    why = PORT_CONFLICT
                    break
        if why is None and (node_selector or node_aff is not None):
            labels = node.meta.labels
            if node_selector and not matches_simple_selector(node_selector, labels):
                why = SELECTOR_MISMATCH
            elif node_aff is not None and not node_aff.matches(labels):
                why = SELECTOR_MISMATCH
        if node_cache is not None:
            # memoize the node-local prefix verdict (why or clean)
            node_cache[1][sig_key] = why
        if why is None and needs_tail:
            # ONE implementation of the cross-node tail for hit and miss
            why = _post_cache_stages(
                pod, meta, info, ctx, has_disk_vols, has_pvc_vols, has_own_aff
            )
        if why is None:
            feasible.append(name)
        else:
            failures[name] = [why]
    return feasible, failures
