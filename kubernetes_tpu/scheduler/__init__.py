"""Scheduler (SURVEY.md L6'): CPU oracle + batched TPU backend seam."""

from .generic_scheduler import FitError, GenericScheduler, ScheduleResult
from .nodeinfo import NodeInfo, SchedulerCache
from .predicates import (
    DEFAULT_PREDICATES,
    PredicateContext,
    PredicateMetadata,
    compute_metadata,
    pod_fits_on_node,
)
from .priorities import (
    BalancedResourceAllocation,
    EqualPriority,
    ImageLocalityPriority,
    InterPodAffinityPriority,
    LeastRequestedPriority,
    MostRequestedPriority,
    NodeAffinityPriority,
    NodePreferAvoidPodsPriority,
    PriorityContext,
    SelectorSpreadPriority,
    TaintTolerationPriority,
    cluster_autoscaler_priorities,
    default_priorities,
)
from .queue import PodBackoff, SchedulingQueue
from .scheduler import Scheduler
from .units import (
    CPU_MILLI,
    GPU_COUNT,
    MAX_PRIORITY,
    MEM_MIB,
    NUM_RESOURCES,
    STORAGE_MIB,
    ResourceVec,
    pod_nonzero_request_vec,
    pod_request_vec,
)
from .extender import ExtenderError, HTTPExtender
from .policy import (
    PolicyError,
    algorithm_from_policy,
    algorithm_from_provider,
    load_policy_file,
)
from .preemption import PreemptionTarget, find_preemption_target
