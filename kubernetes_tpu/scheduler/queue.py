"""Pending-pod FIFO queue + per-pod scheduling backoff.

Capability of the reference's ``podQueue *cache.FIFO``
(``factory/factory.go:75,140``; blocking pop ``getNextPod :782``) and
``util/backoff_utils.go:86 PodBackoff`` (1s initial, 60s max, exponential).

Extra over the reference (the batch seam): ``drain(max_n)`` pops every
currently-pending pod at once — the TPU backend schedules the whole drained
batch in one device program instead of one ``pop()`` per iteration.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..api import types as api
from ..client.workqueue import WorkQueue


class PodBackoff:
    def __init__(
        self,
        initial: float = 1.0,
        max_duration: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.initial = initial
        self.max_duration = max_duration
        self._clock = clock
        self._entries: dict[str, tuple[float, float]] = {}  # key -> (backoff, last_update)
        self._mu = threading.Lock()

    def arm(self, pod_key: str) -> float:
        """Consume one backoff step: returns the duration to wait NOW and
        doubles the stored duration for the next failure (reference
        ``getBackoff``).  Call this only when a failure actually
        happened — read-only probes must use :meth:`peek`."""
        with self._mu:
            backoff, _ = self._entries.get(pod_key, (self.initial, 0.0))
            next_backoff = min(backoff * 2, self.max_duration)
            self._entries[pod_key] = (next_backoff, self._clock())
            return backoff

    def peek(self, pod_key: str) -> float:
        """Inspect without arming: the duration the next :meth:`arm`
        would return.  Split from the arming read (ROADMAP open item) so
        a monitoring/diagnostic probe does not double the pod's penalty
        or refresh its GC timestamp."""
        with self._mu:
            return self._entries.get(pod_key, (self.initial, 0.0))[0]

    def get_backoff(self, pod_key: str) -> float:
        """Deprecated spelling of :meth:`arm` — it ADVANCES the backoff.
        Kept for the reference-shaped name; new probes that only want to
        look must call :meth:`peek`."""
        return self.arm(pod_key)

    def forget(self, pod_key: str) -> None:
        with self._mu:
            self._entries.pop(pod_key, None)

    def gc(self, max_age: float = 600.0) -> None:
        with self._mu:
            now = self._clock()
            for k in [k for k, (_, t) in self._entries.items() if now - t > max_age]:
                del self._entries[k]


class SchedulingQueue:
    """FIFO of pending pods, deduped by key, with delayed re-adds.

    A thin pod-object layer over :class:`~kubernetes_tpu.client.workqueue.
    WorkQueue` (one blocking/dedup/delay implementation in the codebase):
    the workqueue carries keys, this class carries the pod objects.  A key
    whose pod was removed may linger in the workqueue; pops skip such
    phantoms, and ``__len__`` counts live pods only."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._wq = WorkQueue(clock=clock)
        self._mu = threading.Lock()
        self._pods: dict[str, api.Pod] = {}
        self._clock = clock

    def add(self, pod: api.Pod) -> None:
        with self._mu:
            self._pods[pod.meta.key] = pod
        self._wq.add(pod.meta.key)

    def add_after(self, pod: api.Pod, delay: float) -> None:
        with self._mu:
            self._pods[pod.meta.key] = pod
        self._wq.add_after(pod.meta.key, delay)

    def update(self, pod: api.Pod) -> None:
        with self._mu:
            if pod.meta.key in self._pods:
                self._pods[pod.meta.key] = pod

    def remove(self, pod_key: str) -> None:
        with self._mu:
            self._pods.pop(pod_key, None)

    def remove_many(self, pod_keys: list) -> None:
        """Batch remove under ONE lock hold — the scheduler's columnar
        bind confirm clears a whole wave's keys at once (each is a
        no-op dict pop for pods the wave already drained)."""
        with self._mu:
            for key in pod_keys:
                self._pods.pop(key, None)

    def pop(self, timeout: Optional[float] = None) -> Optional[api.Pod]:
        """Blocking FIFO pop (``getNextPod``)."""
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            remaining = None if deadline is None else max(0.0, deadline - self._clock())
            key = self._wq.get(timeout=remaining)
            if key is None:
                return None
            self._wq.done(key)
            with self._mu:
                pod = self._pods.pop(key, None)
            if pod is not None:
                return pod
            # phantom (removed while queued): keep draining

    def drain(self, max_n: Optional[int] = None) -> list[api.Pod]:
        """Pop every currently-ready pod in FIFO order — the batch seam.
        One lock round for the keys, one for the pod map (the per-pod
        pop() path costs four lock rounds each; at 150k pods that's the
        difference between microseconds and a second of pure locking)."""
        keys = self._wq.drain_ready(max_n)
        if not keys:
            return []
        out: list[api.Pod] = []
        with self._mu:
            for key in keys:
                pod = self._pods.pop(key, None)
                if pod is not None:  # phantom: removed while queued
                    out.append(pod)
        return out

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until a key is ready, the queue closes, or the timeout
        elapses — without consuming anything.  The batch loop's
        accumulation primitive (a ready key may still be a phantom; the
        loop's drain skips those as usual)."""
        return self._wq.wait_ready(timeout)

    @property
    def closed(self) -> bool:
        return self._wq.is_shutdown()

    def snapshot_pending(self) -> list[api.Pod]:
        """The live pod objects currently known to the queue (ready or
        delayed), without consuming anything — the overlapped-prep path
        warms per-pod memos (signature/content keys) on these while the
        device executes the current wave."""
        with self._mu:
            return list(self._pods.values())

    def __len__(self) -> int:
        # fast path: nothing delayed (the steady-state accumulation loop
        # polls len() every few ms) — every live pod is ready, no key-set
        # materialization needed
        if self._wq.delayed_count() == 0:
            with self._mu:
                return len(self._pods)
        with self._mu:
            live = set(self._pods)
        # live pods that are ready (not still in the delay heap)
        delayed = self._wq.delayed_keys()
        return len([k for k in live if k not in delayed])

    def pending_delayed(self) -> int:
        delayed = self._wq.delayed_keys()
        with self._mu:
            return len([k for k in delayed if k in self._pods])

    def close(self) -> None:
        self._wq.shut_down()
